"""Training-step observatory: phase-attributed step timelines.

Telemetry (``observability/telemetry.py``) prices a step as one wall
number; this module says where the time went. Every profiled step is a
record of phase spans —

* ``input_wait`` — consumer-side reader/queue starvation, measured at
  the source (``layers/io.py`` / ``reader/decorator.py`` call
  :func:`note_input_wait`; a thread-local accumulator hands the wait to
  the NEXT step that thread runs, so prefetch-thread waits are never
  mis-billed to the training thread),
* ``feed`` — host feed conversion + host->device transfer,
* ``compile`` — executable lookup (cache hit = microseconds; a fresh
  XLA trace shows up here instead of silently fattening the step),
* ``dispatch`` — the jitted call itself (argument marshalling + XLA
  enqueue; chaos' ``exec.dispatch`` faults land inside this bracket),
* ``device`` — block_until_ready on the fetched arrays (annotated with
  ``jax.profiler.TraceAnnotation`` when a trace session is live, so the
  bracket shows up in the device timeline too),
* ``fetch`` — device->host materialization to numpy,
* ``host`` — the residual (record bookkeeping, scope writes, python).

Roofline join: once per executable the step function is re-traced (off
the timed path) and priced by tools/hlo_cost_model.py's fused-group
table — per-step FLOPs, HBM bytes, roofline-predicted time, memory- vs
compute-bound verdict. Each record then carries achieved-FLOP/s,
achieved-MFU and achieved-vs-predicted, and classifies itself
``input`` / ``host`` / ``compute`` / ``bandwidth`` bound.

On top of the stream: a bounded ring exported as
``<metrics_path>.stepprof.jsonl`` through ``telemetry.flush()``,
metrics-registry surfaces (phase histograms, starvation + achieved-MFU
gauges), and an online regression detector — rolling median + MAD per
executable; excursions and sustained drifts emit black-box flight
events naming the guilty phase.

Overhead contract (FLAGS_step_profile, telemetry's discipline): OFF is
one module-attribute read per step — zero allocations, zero fresh
compiles, bit-identical results. ON costs one StepSpan + a handful of
perf_counter calls per step; the cost-model trace is one-shot per
executable and runs after the timed region.
"""

import collections
import threading
import time

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "enable", "reset", "begin", "finish", "records",
    "inflight", "note_input_wait", "note_queue_wait", "cost_table",
    "write_stepprof_jsonl", "StepSpan", "PHASES", "RING_CAP",
    "device_annotation",
]

ENABLED = False

RING_CAP = 2048

# phase vocabulary — the record's "phases" dict only carries nonzero
# entries, but consumers (step_breakdown, perf_ledger) treat this tuple
# as the full axis
PHASES = ("input_wait", "feed", "compile", "dispatch", "device", "fetch",
          "host")

# regression detector: rolling per-executable baseline
_REG_WINDOW = 64     # samples in the rolling median/MAD window
_REG_MIN = 8         # baseline size before the detector speaks
_REG_K = 5.0         # MAD multiplier (5 sigma-equivalents) for excursions
_REG_REL_FLOOR = 0.25  # minimum relative excess — sub-ms steps are noisy
_DRIFT_N = 5         # consecutive excursions = sustained drift, rebase

_lock = lock_witness.make_lock("observability.step_profiler")
_records = collections.deque(maxlen=RING_CAP)
_cost = {}           # fingerprint -> per-step cost join (None = tried, failed)
_reg = {}            # fingerprint/origin -> regression baseline state
_tls = threading.local()   # .input_wait: seconds banked for the next step
# thread ident -> (origin, phase, t_phase, t_step): the in-flight step's
# current bracket, read lock-free by watchdog/blackbox (single-key dict
# ops are atomic under the GIL; a racy read is fine for forensics)
_inflight = {}

# same bucket ladder as telemetry's step histogram: phases span the same
# 100us..100s range a step does
_PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                  50.0, 100.0)

_phase_seconds = REGISTRY.histogram(
    "paddle_tpu_step_phase_seconds",
    "per-step wall seconds attributed to each phase", labels=("phase",),
    buckets=_PHASE_BUCKETS)
_achieved_mfu = REGISTRY.gauge(
    "paddle_tpu_step_achieved_mfu",
    "achieved MFU of the last profiled step (cost-model FLOPs / wall / "
    "peak)")
_starvation = REGISTRY.gauge(
    "paddle_tpu_step_starvation_fraction",
    "input-wait fraction of the last profiled step's wall")
_regressions = REGISTRY.counter(
    "paddle_tpu_step_regressions_total",
    "step-time excursions/drifts flagged by the online detector",
    labels=("kind", "phase"))
_reader_wait = REGISTRY.counter(
    "paddle_tpu_reader_wait_seconds_total",
    "consumer-side seconds blocked waiting on reader queues",
    labels=("site",))
_queue_depth = REGISTRY.gauge(
    "paddle_tpu_reader_queue_depth",
    "items in the reader blocking queue after the last pop")


def enable(on=True):
    """Flip the observatory at runtime (tests, notebooks);
    ``FLAGS_step_profile`` only sets the import-time default."""
    global ENABLED
    ENABLED = bool(on)
    return ENABLED


def reset():
    """Drop the ring, the cost join and the regression baselines (test
    isolation; the executors re-join costs one-shot per executable, so a
    reset mid-run only re-prices on the next new executable)."""
    with _lock:
        _records.clear()
        _cost.clear()
        _reg.clear()
    _inflight.clear()
    _tls.input_wait = 0.0


# -- reader-side starvation accounting ---------------------------------------

def note_input_wait(seconds, site="py_reader"):
    """Bank consumer-side reader wait against the CALLING thread's next
    step. Called by layers/io.py / reader/decorator.py under the
    ENABLED guard; monotonic durations, measured outside any lock."""
    _reader_wait.inc(seconds, site=site)
    _tls.input_wait = getattr(_tls, "input_wait", 0.0) + seconds


def note_queue_wait(seconds, depth, site="reader.queue"):
    """Queue-level pop accounting (BlockingQueue/NativeTensorQueue):
    wait seconds per site plus the post-pop depth gauge. NOT banked
    against a step — prefetch threads pop on their own clock; the
    per-step claim happens at the consumer (:func:`note_input_wait`)."""
    _reader_wait.inc(seconds, site=site)
    _queue_depth.set(depth)


# -- the per-step span -------------------------------------------------------

class StepSpan(object):
    """One step's open record. Executors hold one of these across the
    step and bracket each phase with enter()/exit(); ``finish`` closes
    it into the ring. Plain slots — the ON-path per-step cost is this
    object plus a small dict."""

    __slots__ = ("origin", "t0", "phases", "input_wait", "fingerprint",
                 "_cur", "_t_cur", "_cost_cp", "_cost_avals")

    def __init__(self, origin):
        self.origin = origin
        self.t0 = time.perf_counter()
        self.phases = {}
        self.input_wait = 0.0
        self.fingerprint = None
        self._cur = None
        self._t_cur = 0.0
        self._cost_cp = None
        self._cost_avals = None

    def enter(self, phase):
        now = time.perf_counter()
        self._cur = phase
        self._t_cur = now
        _inflight[threading.get_ident()] = (self.origin, phase, now,
                                            self.t0)

    def exit(self):
        now = time.perf_counter()
        cur = self._cur
        if cur is not None:
            self.phases[cur] = self.phases.get(cur, 0.0) + (now - self._t_cur)
            self._cur = None
            _inflight[threading.get_ident()] = (self.origin, "host", now,
                                                self.t0)

    def pre_dispatch(self, cp, state, feeds, key, program=None):
        """Stamp the executable fingerprint and — one-shot per
        executable — snapshot avals for the deferred cost-model join.
        Must run BEFORE dispatch: the step call donates the mutable
        state buffers, after which their shapes are gone."""
        from paddle_tpu.observability import telemetry as _telemetry

        self.fingerprint = _telemetry.executable_fingerprint(cp, program)
        if getattr(cp, "_stepprof_cost_done", False):
            return
        cp._stepprof_cost_done = True
        try:
            import jax

            aval = jax.ShapeDtypeStruct
            self._cost_avals = (
                {n: aval(state[n].shape, state[n].dtype)
                 for n in cp.mutable_state},
                {n: aval(state[n].shape, state[n].dtype)
                 for n in cp.frozen_state},
                {n: aval(v.shape, v.dtype) for n, v in feeds.items()},
                aval(key.shape, key.dtype),
            )
            self._cost_cp = cp
        except Exception:
            self._cost_avals = None


def begin(origin):
    """Open a span for one step and claim the calling thread's banked
    input wait. Executors call this as
    ``sp = _stepprof.begin(...) if _stepprof.ENABLED else None`` — the
    OFF path is the one attribute read."""
    sp = StepSpan(origin)
    banked = getattr(_tls, "input_wait", 0.0)
    if banked:
        sp.input_wait = banked
        _tls.input_wait = 0.0
    return sp


class _NullAnnotation(object):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_ANNOTATION = _NullAnnotation()


def device_annotation(name="paddle_tpu.step.device"):
    """The device-phase bracket's trace annotation: a real
    ``jax.profiler.TraceAnnotation`` when profiler.start_profiler opened
    a trace session (so the bracket lands in the device timeline), else
    a shared no-op context."""
    try:
        from paddle_tpu import profiler as _profiler

        if _profiler._state.get("jax_trace_dir"):
            import jax

            return jax.profiler.TraceAnnotation(name)
    except Exception:
        pass
    return _NULL_ANNOTATION


# -- cost-model join ---------------------------------------------------------

def _join_cost(sp, steps):
    """Price the executable with the hlo_cost_model fused-group table
    (one-shot per fingerprint; runs in ``finish``, off the timed path).
    Stores PER-STEP numbers — multi-step scans divide by the scan
    length so a 32-step dispatch prices like 32 single steps."""
    fp = sp.fingerprint
    cp, avals = sp._cost_cp, sp._cost_avals
    sp._cost_cp = sp._cost_avals = None
    if not fp or fp in _cost or cp is None or avals is None:
        return
    entry = None
    try:
        import jax

        from paddle_tpu.observability import _cost_model

        mod = _cost_model.load()
        closed = jax.make_jaxpr(cp.jitted)(*avals)
        jaxpr = closed.jaxpr
        while (len(jaxpr.eqns) == 1
               and jaxpr.eqns[0].primitive.name in ("pjit", "jit")):
            inner = jaxpr.eqns[0].params.get("jaxpr")
            if inner is None:
                break
            jaxpr = getattr(inner, "jaxpr", inner)
        opt = mod.optimize_jaxpr(jaxpr)
        groups = mod.analyze(opt)
        flops = float(sum(g.flops for g in groups))
        hbm = float(sum(g.bytes_total() for g in groups))
        k = float(max(1, steps))
        # roofline-predicted step time at nameplate peaks: each fused
        # group pays max(compute, HBM) — the cost model's pricing rule
        roof = sum(max(g.flops / mod.PEAK_FLOPS,
                       g.bytes_total() / mod.HBM_BW) for g in groups)
        roof_obs = sum(max(g.flops / mod.OBSERVED_PEAK_FLOPS,
                           g.bytes_total() / mod.HBM_BW) for g in groups)
        entry = {
            "flops": flops / k,
            "hbm_bytes": hbm / k,
            "roofline_s": roof / k,
            "roofline_observed_s": roof_obs / k,
            "groups": len(groups),
            "bound": ("hbm" if hbm / mod.HBM_BW > flops / mod.PEAK_FLOPS
                      else "mxu"),
            "nameplate_peak_flops": float(mod.PEAK_FLOPS),
        }
    except Exception:
        entry = None
    with _lock:
        _cost.setdefault(fp, entry)


def cost_table():
    """The per-executable cost join (tests, step_breakdown)."""
    with _lock:
        return {k: (dict(v) if v else None) for k, v in _cost.items()}


# -- regression detector -----------------------------------------------------

def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _detect_regression(key, step_s, per_step_phases):
    """Rolling median+MAD excursion/drift detector. Called under _lock.
    Healthy steps extend the baseline; excursions do not (one slow step
    must not drag the median up), but _DRIFT_N consecutive excursions
    are accepted as a new regime: one 'drift' event, then rebase."""
    st = _reg.get(key)
    if st is None:
        st = {"window": collections.deque(maxlen=_REG_WINDOW),
              "phases": {}, "streak": 0}
        _reg[key] = st
    window = st["window"]
    verdict = None
    if len(window) >= _REG_MIN:
        med = _median(window)
        mad = _median([abs(x - med) for x in window])
        thresh = med + max(_REG_K * 1.4826 * mad, _REG_REL_FLOOR * med)
        if step_s > thresh:
            # the guilty phase: largest absolute excess over its own
            # rolling median
            guilty, excess, guilty_s, guilty_med = "host", 0.0, 0.0, 0.0
            for ph, cur in per_step_phases.items():
                base = st["phases"].get(ph)
                pmed = _median(base) if base else 0.0
                if cur - pmed > excess:
                    guilty, excess = ph, cur - pmed
                    guilty_s, guilty_med = cur, pmed
            st["streak"] += 1
            kind = "drift" if st["streak"] >= _DRIFT_N else "excursion"
            verdict = {
                "kind": kind, "phase": guilty,
                "step_s": step_s, "median_s": med, "threshold_s": thresh,
                "phase_s": guilty_s, "phase_median_s": guilty_med,
            }
            if kind == "drift":
                # sustained: accept the new regime so the detector does
                # not alarm on every step forever
                window.clear()
                st["phases"].clear()
                st["streak"] = 0
                window.append(step_s)
            return verdict
    st["streak"] = 0
    window.append(step_s)
    for ph, cur in per_step_phases.items():
        dq = st["phases"].get(ph)
        if dq is None:
            dq = st["phases"][ph] = collections.deque(maxlen=_REG_WINDOW)
        dq.append(cur)
    return verdict


# -- closing a span ----------------------------------------------------------

def finish(sp, steps=1, feeds=None, fetches=None, dispatch_only=False):
    """Close a span into a phase-attributed record: residual-host
    accounting, the cost-model join, achieved-MFU, boundedness verdict,
    regression detection, ring append + metric writes. Runs entirely
    after the step's timed region — ``feeds``/``fetches`` are passed as
    containers (not pre-summed byte counts) so the wall clock stops on
    the FIRST line here, before any accounting arithmetic."""
    now = time.perf_counter()
    if sp._cur is not None:
        sp.exit()
    _inflight.pop(threading.get_ident(), None)
    steps = max(1, int(steps))
    wall = now - sp.t0
    feed_bytes = (sum(getattr(a, "nbytes", 0) for a in feeds.values())
                  if feeds else 0)
    fetch_bytes = (sum(getattr(f, "nbytes", 0) for f in fetches)
                   if fetches else 0)
    measured = sum(sp.phases.values())
    host = max(0.0, wall - measured)
    step_wall = wall + sp.input_wait
    phases = dict(sp.phases)
    phases["host"] = host
    if sp.input_wait:
        phases["input_wait"] = sp.input_wait
    # coverage: every explicitly measured second (brackets + source-side
    # input wait) over the step's full wall — the ≥0.95 CI gate
    coverage = ((measured + sp.input_wait) / step_wall
                if step_wall > 0 else 1.0)
    starvation = sp.input_wait / step_wall if step_wall > 0 else 0.0
    step_s = step_wall / steps

    _join_cost(sp, steps)
    cost = _cost.get(sp.fingerprint) if sp.fingerprint else None

    rec = {
        "ts": time.time(),
        "origin": sp.origin,
        "fingerprint": sp.fingerprint,
        "steps": steps,
        "wall_s": wall,
        "step_s": step_s,
        "phases": {p: v for p, v in phases.items() if v > 0.0},
        "coverage": coverage,
        "starvation_fraction": starvation,
        "feed_bytes": int(feed_bytes),
        "fetch_bytes": int(fetch_bytes),
    }
    if dispatch_only:
        # async handles: the span measures host dispatch latency, not a
        # step — excluded from MFU, starvation and the detector
        rec["dispatch_only"] = True
    achieved_mfu = None
    if cost and step_s > 0 and not dispatch_only:
        from paddle_tpu.observability import telemetry as _telemetry

        achieved = cost["flops"] / step_s
        # peak: flag override, then the chip table; on hardware the
        # table misses (CPU proxy runs) fall back to the cost model's
        # nameplate so MFU stays finite and comparable run-to-run
        peak = _telemetry.peak_flops() or cost["nameplate_peak_flops"]
        rec["flops_per_step"] = cost["flops"]
        rec["hbm_bytes_per_step"] = cost["hbm_bytes"]
        rec["achieved_flops_per_sec"] = achieved
        rec["achieved_mfu"] = achieved_mfu = achieved / peak
        rec["roofline_s"] = cost["roofline_s"]
        rec["predicted_ratio"] = (step_s / cost["roofline_s"]
                                  if cost["roofline_s"] > 0 else None)
    rec["bound"] = _classify(phases, sp.input_wait, cost)

    verdict = None
    if not dispatch_only:
        per_step_phases = {p: v / steps for p, v in phases.items()}
        with _lock:
            verdict = _detect_regression(sp.fingerprint or sp.origin,
                                         step_s, per_step_phases)
            if verdict:
                rec["regression"] = dict(verdict)
            _records.append(rec)
    else:
        with _lock:
            _records.append(rec)

    # metric writes outside the ring lock (each metric has its own)
    for p, v in rec["phases"].items():
        _phase_seconds.observe(v / steps, phase=p)
    if not dispatch_only:
        _starvation.set(starvation)
        if achieved_mfu is not None:
            _achieved_mfu.set(achieved_mfu)
    if verdict:
        _regressions.inc(1, kind=verdict["kind"], phase=verdict["phase"])
        from paddle_tpu.observability import blackbox as _blackbox

        # direct record() — regressions are rare and exactly what the
        # flight recorder exists for, so they land even when blackbox's
        # exception hooks are not armed. The verdict's own "kind"
        # (spike/drift) must not collide with record()'s event kind.
        fields = dict(verdict)
        fields["regression"] = fields.pop("kind")
        _blackbox.record(
            "step_regression", origin=sp.origin,
            fingerprint=(sp.fingerprint or "")[:16], **fields)
    return rec


def _classify(phases, input_wait, cost):
    """The step's boundedness verdict: ``input`` when starvation
    dominates, ``host`` when host-side phases outweigh device time,
    else the cost model's compute/bandwidth call (``device`` when the
    executable was never priced)."""
    device_s = phases.get("device", 0.0)
    host_s = sum(v for p, v in phases.items()
                 if p not in ("device", "input_wait"))
    if input_wait >= max(device_s, host_s) and input_wait > 0:
        return "input"
    if host_s > device_s:
        return "host"
    if cost:
        return "compute" if cost["bound"] == "mxu" else "bandwidth"
    return "device"


# -- introspection + export --------------------------------------------------

def records():
    """Snapshot of the ring (oldest first)."""
    with _lock:
        return [dict(r) for r in _records]


def inflight():
    """The current in-flight step bracket per thread — the watchdog's
    'which phase is stalled' answer. Lock-free reads of the _inflight
    dict: safe from signal handlers and the watchdog thread."""
    now = time.perf_counter()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, ent in list(_inflight.items()):
        origin, phase, t_phase, t_step = ent
        out.append({
            "thread": names.get(tid, str(tid)),
            "origin": origin,
            "phase": phase,
            "phase_age_s": round(now - t_phase, 3),
            "step_age_s": round(now - t_step, 3),
        })
    return out


def write_stepprof_jsonl(path, mode="w"):
    """One JSON line per profiled step — the file
    tools/step_breakdown.py --steps and tools/perf_ledger.py consume.
    telemetry.flush() writes it as ``<metrics_path>.stepprof.jsonl``."""
    import json

    recs = records()
    with open(path, mode) as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")
    return len(recs)


def _init_from_flags():
    from paddle_tpu import flags

    try:
        enable(flags.get("step_profile"))
    except KeyError:  # pragma: no cover - flag table always has it
        pass


_init_from_flags()
