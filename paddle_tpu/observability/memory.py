"""HBM X-ray: live-buffer ledger, predicted-memory planning, OOM forensics.

PR 2/4 made *time* observable (step telemetry, MFU, stragglers) and PR 4/5
made *failures* observable (black box, NaN provenance, classified retry).
This module does the same for *memory* — the resource every roadmap item
(GSPMD sharding, buffer donation, serving capacity) budgets against:

* **Live-buffer ledger** — the executors, the feed/fetch paths, the
  exec-cache AOT loader and the checkpoint snapshotter register device
  buffers as they enter/leave scopes, classified by kind
  (``param | opt_state | activation | feed | cache``). Exported as
  ``paddle_tpu_hbm_live_bytes{device,kind}`` gauges; a per-step peak
  watermark lands in every telemetry step record (``peak_hbm_bytes``).
  XLA owns the real allocator, so the ledger is the *accountable* view:
  what the framework asked to keep alive, by name — the thing an OOM
  post-mortem needs and ``memory_stats()`` on the backend can't give
  (and on CPU/older runtimes the backend gives nothing at all).

* **Memory plan** — :func:`plan_program` (surfaced as
  ``Program.memory_plan(feed_shapes)``) walks the PR 3 liveness analysis
  with byte accounting in the spirit of ``tools/hlo_cost_model.py``'s
  ``_nbytes`` and reports the predicted high-water mark, the op at which
  it occurs, and the top-K live tensors there. Executors register the
  plan per compiled executable, so predicted-vs-measured peak is a
  first-class report (``profiler.memory_stats()``,
  ``tools/step_breakdown.py --memory``, bench.py artifacts).

* **OOM forensics** — :func:`enrich_and_raise` upgrades a
  ``RESOURCE_EXHAUSTED``-style failure into diagnostic rule **M001**
  (never retried — see resilience/retry.py): a black-box dump carrying
  the ledger's top holders, the predicted peak, and actionable hints
  (enable donation, shrink the batch, shard an axis).

Overhead contract: every executor hook guards on the module bool
``ENABLED`` (mirrors telemetry's switch); ``FLAGS_telemetry=0`` leaves
the hot path untouched. The OOM catch costs one substring check on the
failure path only.
"""

import threading

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "enable", "reset", "KINDS", "track", "drop",
    "live_bytes", "live_by_kind", "live_by_device", "top_holders",
    "track_state_sharded",
    "take_step_peak", "register_plan", "predicted_peak", "last_plan",
    "plan_program", "MemoryPlan", "is_oom", "MemoryExhaustedError",
    "enrich_and_raise", "RULE", "RULE_NAME",
]

ENABLED = False

KINDS = ("param", "opt_state", "activation", "feed", "cache")

RULE = "M001"
RULE_NAME = "hbm-exhausted"

_lock = lock_witness.make_lock("observability.memory")
_live = {}          # (device, kind, name) -> bytes
_totals = {}        # (device, kind) -> bytes (kept incrementally)
_peak = [0]         # high-water mark of sum(_totals) since take_step_peak
_plans = {}         # fingerprint -> plan dict (bounded FIFO)
_last_plan = [None]
_PLAN_CAP = 64

_live_gauge = REGISTRY.gauge(
    "paddle_tpu_hbm_live_bytes",
    "bytes the framework holds live per device, by buffer kind "
    "(ledger view: params, optimizer state, activations, feeds, caches)",
    labels=("device", "kind"))
_oom_total = REGISTRY.counter(
    "paddle_tpu_oom_total",
    "RESOURCE_EXHAUSTED/OOM failures enriched as M001 diagnostics",
    labels=("origin",))


def enable(on=True):
    """Flip the ledger (telemetry.enable keeps it in lockstep)."""
    global ENABLED
    ENABLED = bool(on)
    return ENABLED


def reset():
    """Drop the ledger, watermark and registered plans (tests)."""
    with _lock:
        for (device, kind) in _totals:
            _live_gauge.set(0, device=device, kind=kind)
        _live.clear()
        _totals.clear()
        _peak[0] = 0
        _plans.clear()
        _last_plan[0] = None


# -- the ledger --------------------------------------------------------------

def track(name, nbytes, kind, device="host"):
    """Register (or replace) one live buffer. Re-tracking the same
    (device, kind, name) key replaces the old entry — the scope-binding
    pattern where a donated buffer's successor takes its name — so the
    ledger balances without an explicit release. Callers guard on
    ``ENABLED``; calling directly always records."""
    nbytes = int(nbytes)
    key = (device, kind, name)
    # Timed acquire [C003]: track/drop run inside the SIGTERM handler
    # chain (snapshot ledger of the final checkpoint), where the signal
    # may have interrupted this very thread mid-ledger-update; the
    # ledger is advisory accounting, so a skipped entry beats a process
    # that cannot die.
    if _lock.acquire(timeout=1.0):
        try:
            old = _live.get(key, 0)
            _live[key] = nbytes
            tot = _totals.get((device, kind), 0) + nbytes - old
            _totals[(device, kind)] = tot
            _live_gauge.set(tot, device=device, kind=kind)
            total = sum(_totals.values())
            if total > _peak[0]:
                _peak[0] = total
        finally:
            _lock.release()
    return key


def drop(name, kind, device="host"):
    """Release one tracked buffer; unknown keys are a no-op (a buffer
    can leave through more than one path — e.g. an async fetch whose
    handle materializes after the sync path already swept)."""
    key = (device, kind, name)
    # timed for the same reason as track() [C003]
    if not _lock.acquire(timeout=1.0):
        return False
    try:
        old = _live.pop(key, None)
        if old is None:
            return False
        tot = _totals.get((device, kind), 0) - old
        _totals[(device, kind)] = tot
        _live_gauge.set(tot, device=device, kind=kind)
    finally:
        _lock.release()
    return True


def live_bytes():
    with _lock:
        return sum(_totals.values())


def live_by_kind():
    out = {}
    with _lock:
        for (_device, kind), b in _totals.items():
            if b:
                out[kind] = out.get(kind, 0) + b
    return out


def live_by_device():
    out = {}
    with _lock:
        for (device, _kind), b in _totals.items():
            if b:
                out[device] = out.get(device, 0) + b
    return out


def top_holders(k=3):
    """The K largest live buffers: ``[{"name", "kind", "device",
    "bytes"}]``, largest first — the first question an OOM autopsy asks."""
    with _lock:
        entries = sorted(_live.items(), key=lambda kv: -kv[1])[:max(0, k)]
    return [{"name": name, "kind": kind, "device": device, "bytes": b}
            for (device, kind, name), b in entries if b]


def take_step_peak():
    """The high-water mark of total ledger bytes since the last call
    (telemetry.record_step's per-step watermark). Resets the mark to the
    CURRENT total so long-lived state keeps counting next step."""
    with _lock:
        peak = _peak[0]
        _peak[0] = sum(_totals.values())
    return peak


# -- executor-facing hooks ---------------------------------------------------

def _state_kinds(cp, program, names):
    """{state var name -> 'param'|'opt_state'}, cached on the compiled
    program (classification walks the graph once per executable)."""
    kinds = getattr(cp, "_mem_kinds", None)
    if kinds is None:
        from paddle_tpu import framework

        block = program.global_block()
        kinds = {}
        for n in names:
            v = block._find_var_recursive(n)
            kinds[n] = ("param" if isinstance(v, framework.Parameter)
                        else "opt_state")
        cp._mem_kinds = kinds
    return kinds


def track_feeds(feeds, device):
    for name, arr in feeds.items():
        track(name, getattr(arr, "nbytes", 0), "feed", device)


def drop_feeds(feeds, device):
    for name in feeds:
        drop(name, "feed", device)


def track_state(cp, program, new_state, device):
    """Scope binding after a dispatch: the step's output state replaces
    the (donated) inputs under the same names, so re-tracking IS the
    release of the consumed buffers."""
    kinds = _state_kinds(cp, program, list(new_state))
    for name, val in new_state.items():
        track(name, getattr(val, "nbytes", 0),
              kinds.get(name, "opt_state"), device)


def track_state_sharded(cp, program, new_state, fallback_device="mesh"):
    """Mesh-path scope binding: book each state var's REAL per-device
    shard bytes under per-device labels, not one mesh-wide logical entry.
    A param sharded over a 4-way ``fsdp`` axis shows ~1/4 of its bytes on
    each device's ``paddle_tpu_hbm_live_bytes{device,kind}`` series while
    replicated state shows full bytes on every device — the measured half
    of the derived-plan story (the predicted half is ``memory_plan`` with
    ``shard_factors``)."""
    from paddle_tpu.observability.telemetry import device_label

    kinds = _state_kinds(cp, program, list(new_state))
    for name, val in new_state.items():
        kind = kinds.get(name, "opt_state")
        try:
            shards = val.addressable_shards
        except Exception:
            shards = None
        if not shards:
            track(name, getattr(val, "nbytes", 0), kind, fallback_device)
            continue
        per_dev = {}
        for sh in shards:
            lbl = device_label(sh.device)
            per_dev[lbl] = per_dev.get(lbl, 0) + int(
                getattr(sh.data, "nbytes", 0))
        for lbl, nb in per_dev.items():
            track(name, nb, kind, lbl)


def track_fetches(fetch_names, fetches, device):
    for name, val in zip(fetch_names, fetches):
        track(name, getattr(val, "nbytes", 0), "activation", device)


def drop_fetches(fetch_names, device):
    for name in fetch_names:
        drop(name, "activation", device)


# -- predicted-memory planning -----------------------------------------------

class MemoryPlan(object):
    """Result of :func:`plan_program`: the predicted high-water mark of
    one step's resident bytes, where it happens, and who holds it.

    Attributes: ``peak_bytes``, ``peak_op_idx`` (index into block 0; the
    peak is measured *entering* that op), ``peak_op_type``, ``n_ops``,
    ``per_op_bytes`` (list, resident bytes entering each op).
    """

    def __init__(self, peak_bytes, peak_op_idx, peak_op_type, n_ops,
                 per_op_bytes, live_at_peak):
        self.peak_bytes = int(peak_bytes)
        self.peak_op_idx = peak_op_idx
        self.peak_op_type = peak_op_type
        self.n_ops = n_ops
        self.per_op_bytes = per_op_bytes
        self._live_at_peak = live_at_peak  # [(name, bytes)] desc

    def top(self, k=5):
        """The K largest tensors live at the predicted peak."""
        return list(self._live_at_peak[:max(0, k)])

    def as_dict(self, top_k=5):
        return {
            "peak_bytes": self.peak_bytes,
            "peak_op_idx": self.peak_op_idx,
            "peak_op_type": self.peak_op_type,
            "n_ops": self.n_ops,
            "top_live": [list(t) for t in self.top(top_k)],
        }

    def __repr__(self):
        return ("MemoryPlan(peak=%d bytes at op %s (%s) of %d)"
                % (self.peak_bytes, self.peak_op_idx, self.peak_op_type,
                   self.n_ops))


def _var_nbytes(block, name, feed_shapes, default_batch):
    """Bytes of one named var: declared shape x dtype itemsize, with feed
    shapes overriding and unknown/dynamic (-1) dims priced at the feed
    batch — the hlo_cost_model ``_nbytes`` discipline applied to VarDescs
    instead of avals."""
    import numpy as np

    from paddle_tpu.core.types import np_dtype

    v = block._find_var_recursive(name)
    if v is None:
        return 0
    shape = (feed_shapes or {}).get(name)
    if shape is None:
        shape = v.shape
    if shape is None:
        return 0
    size = 1
    for d in shape:
        d = int(d)
        size *= d if d > 0 else default_batch
    try:
        item = np.dtype(np_dtype(v.dtype)).itemsize
    except Exception:
        item = 4
    return size * item


def plan_program(program, feed_shapes=None, fetch_names=(),
                 shard_factors=None):
    """Predict one step's HBM high-water mark from the liveness analysis.

    Sweeps block 0's live ranges (analysis/liveness.py): every var is
    resident from its defining op (or op 0 for block inputs: feeds,
    params, state) through its last use (through the whole block when it
    escapes — fetched or persistable). The per-op resident-byte curve's
    maximum is the predicted peak; XLA's scheduler can only do better
    than this program-order bound by reordering, and worse only through
    fragmentation — so it brackets the measured watermark.

    ``shard_factors`` ({var name -> ways split}, from a derived
    GSPMD plan via ``parallel.sharding.plan_shard_factors``) divides
    those vars' bytes, making the predicted peak PER-DEVICE residency
    under the plan instead of logical bytes.
    """
    from paddle_tpu.analysis import liveness

    feed_shapes = {n: tuple(int(d) for d in s)
                   for n, s in (feed_shapes or {}).items()}
    default_batch = 1
    for s in feed_shapes.values():
        if s and int(s[0]) > 0:
            default_batch = max(default_batch, int(s[0]))
    info = liveness.analyze(program, fetch_names=tuple(fetch_names))
    b0 = info.block(0)
    block = program.global_block()
    n_ops = max(1, b0.n_ops)
    # sweep: +bytes at first-def (block inputs at 0), -bytes after last use
    deltas = [0] * (n_ops + 1)
    sizes = {}
    shard_factors = shard_factors or {}
    for name, (d, u) in b0.live_ranges.items():
        nb = _var_nbytes(block, name, feed_shapes, default_batch)
        nb //= max(1, int(shard_factors.get(name, 1)))
        if nb <= 0:
            continue
        start = 0 if d is None else min(d, n_ops - 1)
        v = block._find_var_recursive(name)
        if v is not None and v.persistable:
            # read-modify-write state (a param the optimizer updates) has
            # a first DEF deep in the block, but the buffer arrives as a
            # block input — resident from op 0
            start = 0
        # u is None: defined but never read and not escaping — resident
        # only at its defining op, not through the block's end
        last = max(start, start if u is None else min(u, n_ops - 1))
        sizes[name] = (start, last, nb)
        deltas[start] += nb
        deltas[last + 1] -= nb
    per_op = []
    resident = 0
    for i in range(n_ops):
        resident += deltas[i]
        per_op.append(resident)
    peak_idx = max(range(n_ops), key=lambda i: per_op[i]) if per_op else 0
    peak = per_op[peak_idx] if per_op else 0
    live_at_peak = sorted(
        ((name, nb) for name, (start, last, nb) in sizes.items()
         if start <= peak_idx <= last),
        key=lambda t: -t[1])
    op_type = (block.ops[peak_idx].type
               if 0 <= peak_idx < len(block.ops) else None)
    return MemoryPlan(peak, peak_idx, op_type, n_ops, per_op, live_at_peak)


def register_plan(fingerprint, plan):
    """File one executable's predicted plan (executor, once per compile
    while telemetry is on) so step records and OOM dumps can report
    predicted-vs-measured without recomputing."""
    if not fingerprint or plan is None:
        return
    d = plan.as_dict() if isinstance(plan, MemoryPlan) else dict(plan)
    with _lock:
        _plans[fingerprint] = d
        _last_plan[0] = d
        while len(_plans) > _PLAN_CAP:
            _plans.pop(next(iter(_plans)))


def register_plan_for(cp, program, feed_specs, fingerprint,
                      shard_factors=None, mesh_devices=None):
    """One-shot per compiled executable (executor call sites, guarded on
    telemetry): compute and file the program's predicted plan under its
    telemetry fingerprint. ``shard_factors`` (derived GSPMD plan) makes
    the prediction per-device; pass ``mesh_devices`` alongside so
    ``profiler.memory_stats()`` can scale the per-device peak back to
    the mesh-wide total the measured watermark sums (exact for sharded
    vars, an underestimate for replicated ones — it brackets).
    Best-effort — planning must never break a step."""
    if getattr(cp, "_memory_plan_done", False):
        return None
    cp._memory_plan_done = True
    try:
        plan = plan_program(
            program,
            feed_shapes={n: s for n, (s, _d) in feed_specs.items()},
            fetch_names=cp.fetch_names,
            shard_factors=shard_factors)
    except Exception:
        return None
    d = plan.as_dict()
    if mesh_devices and int(mesh_devices) > 1 and shard_factors:
        d["mesh_devices"] = int(mesh_devices)
    register_plan(fingerprint, d)
    return plan


def predicted_peak(fingerprint=None):
    """Predicted peak bytes for one executable, or — with no fingerprint
    — the most recently registered plan. An explicit fingerprint with no
    registered plan returns None rather than falling back: reporting
    another executable's prediction as this one's would be a silent,
    plausible-looking misattribution in the step records."""
    with _lock:
        if fingerprint is not None:
            plan = _plans.get(fingerprint)
            return plan["peak_bytes"] if plan else None
        if _last_plan[0] is not None:
            return _last_plan[0]["peak_bytes"]
    return None


def last_plan():
    with _lock:
        return dict(_last_plan[0]) if _last_plan[0] else None


def plans():
    with _lock:
        return {k: dict(v) for k, v in _plans.items()}


# -- OOM forensics (rule M001) -----------------------------------------------

# substrings of allocator-failure messages across backends (XLA's
# RESOURCE_EXHAUSTED status, TFRT/PJRT "Out of memory", host MemoryError
# reprs). Deliberately specific: a user ValueError mentioning "memory"
# must not be reclassified.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating", "failed to allocate")


class MemoryExhaustedError(RuntimeError):
    """A RESOURCE_EXHAUSTED dispatch failure upgraded with forensics:
    ``.diagnostic`` carries the M001 finding (top ledger holders,
    predicted peak). The message keeps the original allocator text, so
    handlers matching RESOURCE_EXHAUSTED still match — and
    resilience/retry.py classifies it never-transient either way."""

    def __init__(self, message, diagnostic=None):
        super(MemoryExhaustedError, self).__init__(message)
        self.diagnostic = diagnostic


def is_oom(exc):
    """True for allocator-exhaustion failures: deterministic for a given
    program and batch, so retrying burns accelerator-hours replaying the
    same death — resilience/retry.py vetoes on this."""
    if isinstance(exc, (MemoryExhaustedError, MemoryError)):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def _fmt_mb(b):
    b = int(b)
    if b >= 10e6:
        return "%.1f MB" % (b / 1e6)
    if b >= 10e3:
        return "%.1f KB" % (b / 1e3)
    return "%d B" % b


def oom_diagnostic(origin="dispatch"):
    """Build the M001 Diagnostic from the current ledger + the last
    registered plan (also used directly by tests/tools)."""
    from paddle_tpu.analysis.diagnostics import Diagnostic

    holders = top_holders(3)
    plan = last_plan()
    parts = ["device memory exhausted during %s: ledger holds %s live"
             % (origin, _fmt_mb(live_bytes()))]
    if holders:
        parts.append("top holders: " + ", ".join(
            "%s (%s, %s, %s)" % (h["name"], h["kind"], h["device"],
                                 _fmt_mb(h["bytes"])) for h in holders))
    if plan:
        parts.append("predicted peak %s entering op %s (%s)"
                     % (_fmt_mb(plan["peak_bytes"]), plan["peak_op_idx"],
                        plan["peak_op_type"]))
    hints = ["enable buffer donation for mutable state (run the training "
             "step, not a clone, so optimizer state updates in place)",
             "shrink the batch / sequence dims of the largest holders"]
    if holders and holders[0]["kind"] == "param":
        hints.append("shard parameters along a mesh axis "
                     "(ParallelExecutor / GSPMD) so each chip holds 1/N")
    elif holders and holders[0]["kind"] == "cache":
        hints.append("bound the executable/AOT caches "
                     "(FLAGS_exec_cache_max_bytes)")
    else:
        hints.append("shard the activation-heavy axis across the mesh, "
                     "or rematerialize (FLAGS_remat_gradients)")
    return Diagnostic(
        RULE, RULE_NAME, "error", "; ".join(parts),
        block_idx=0,
        op_idx=plan["peak_op_idx"] if plan else None,
        op_type=plan["peak_op_type"] if plan else None,
        var_names=tuple(h["name"] for h in holders),
        hint="; ".join(hints))


def enrich_and_raise(exc, origin="dispatch"):
    """The dispatch paths' OOM handler: classify as M001, file the
    finding + ledger snapshot with the black box (and dump), count it,
    and raise :class:`MemoryExhaustedError` chained on the allocator
    error. Never retried: resilience/retry.py classifies OOM (and this
    wrapper) never-transient, so no retry budget is burned replaying a
    deterministic death."""
    from paddle_tpu.observability import blackbox

    diag = oom_diagnostic(origin=origin)
    _oom_total.inc(origin=origin)
    blackbox.record_oom_diagnostic(
        diag, top_holders=top_holders(3),
        predicted_peak_bytes=predicted_peak(),
        live_bytes=live_bytes())
    if blackbox.ENABLED:
        blackbox.dump(reason="oom_diagnostic")
    raise MemoryExhaustedError(
        "%s\n%s\n        hint: %s" % (str(exc), str(diag).split("\n")[0],
                                      diag.hint),
        diagnostic=diag) from exc
