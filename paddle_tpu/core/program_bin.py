"""Language-neutral binary serialization of Program IR ("PTPB" format).

Reference parity: ``paddle/fluid/framework/framework.proto`` +
``program_desc.h`` — the reference serializes ProgramDesc as protobuf so the
C++ runtime, Python front-end, transpilers and the inference engine all
share one IR. Here the same role is played by a compact little-endian
tag-length-value format implemented twice: this module (Python) and
``native/src/program.cc`` (C++), with round-trip tests keeping them in
lockstep. Used by save/load_inference_model and the C++ predictor.

Layout (all ints little-endian):
  file   := magic "PTPB" | u32 version | u64 random_seed | u32 nblocks
            | block*
  block  := i32 idx | i32 parent_idx | i32 forward_block_idx
            | u32 nvars | var* | u32 nops | op*
  var    := str name | str type | u8 has_dtype [str dtype]
            | u8 has_shape [u32 ndim, i64*ndim] | u32 lod_level
            | u8 flags (1=persistable, 2=stop_gradient, 4=is_data,
                        8=is_parameter, 16=trainable)
  op     := str type | u32 nslots_in  | (str slot, u32 n, str*n)*
            | u32 nslots_out | same | u32 nattrs | (str name, attr)*
  attr   := u8 tag | value      tags: 0 i64, 1 f64, 2 str, 3 bool,
            4 i64-list, 5 f64-list, 6 str-list, 7 none
  str    := u32 len | utf-8 bytes
"""

import struct

MAGIC = b"PTPB"
VERSION = 1

_ATTR_INT, _ATTR_FLOAT, _ATTR_STR, _ATTR_BOOL = 0, 1, 2, 3
_ATTR_INTS, _ATTR_FLOATS, _ATTR_STRS, _ATTR_NONE = 4, 5, 6, 7


class _Writer(object):
    def __init__(self):
        self.parts = []

    def u8(self, v):
        self.parts.append(struct.pack("<B", v))

    def u32(self, v):
        self.parts.append(struct.pack("<I", v))

    def i32(self, v):
        self.parts.append(struct.pack("<i", v))

    def i64(self, v):
        self.parts.append(struct.pack("<q", v))

    def u64(self, v):
        self.parts.append(struct.pack("<Q", v))

    def f64(self, v):
        self.parts.append(struct.pack("<d", v))

    def s(self, v):
        b = v.encode("utf-8")
        self.u32(len(b))
        self.parts.append(b)

    def bytes(self):
        return b"".join(self.parts)


class _Reader(object):
    def __init__(self, data):
        self.data = data
        self.off = 0

    def _unpack(self, fmt, size):
        v = struct.unpack_from(fmt, self.data, self.off)[0]
        self.off += size
        return v

    def u8(self):
        return self._unpack("<B", 1)

    def u32(self):
        return self._unpack("<I", 4)

    def i32(self):
        return self._unpack("<i", 4)

    def i64(self):
        return self._unpack("<q", 8)

    def u64(self):
        return self._unpack("<Q", 8)

    def f64(self):
        return self._unpack("<d", 8)

    def s(self):
        n = self.u32()
        v = self.data[self.off:self.off + n].decode("utf-8")
        self.off += n
        return v


def _write_attr(w, val):
    if val is None:
        w.u8(_ATTR_NONE)
    elif isinstance(val, bool):
        w.u8(_ATTR_BOOL)
        w.u8(1 if val else 0)
    elif isinstance(val, int):
        w.u8(_ATTR_INT)
        w.i64(val)
    elif isinstance(val, float):
        w.u8(_ATTR_FLOAT)
        w.f64(val)
    elif isinstance(val, str):
        w.u8(_ATTR_STR)
        w.s(val)
    elif isinstance(val, (list, tuple)):
        items = list(val)
        if items and all(isinstance(i, str) for i in items):
            w.u8(_ATTR_STRS)
            w.u32(len(items))
            for i in items:
                w.s(i)
        elif any(isinstance(i, float) for i in items):
            w.u8(_ATTR_FLOATS)
            w.u32(len(items))
            for i in items:
                w.f64(float(i))
        else:
            w.u8(_ATTR_INTS)
            w.u32(len(items))
            for i in items:
                w.i64(int(i))
    else:
        raise TypeError(
            "attr value %r (%s) is not serializable" % (val, type(val))
        )


def _read_attr(r):
    tag = r.u8()
    if tag == _ATTR_NONE:
        return None
    if tag == _ATTR_BOOL:
        return bool(r.u8())
    if tag == _ATTR_INT:
        return r.i64()
    if tag == _ATTR_FLOAT:
        return r.f64()
    if tag == _ATTR_STR:
        return r.s()
    if tag == _ATTR_INTS:
        return [r.i64() for _ in range(r.u32())]
    if tag == _ATTR_FLOATS:
        return [r.f64() for _ in range(r.u32())]
    if tag == _ATTR_STRS:
        return [r.s() for _ in range(r.u32())]
    raise ValueError("bad attr tag %d" % tag)


def serialize_program(program):
    """Program -> bytes (the PTPB flat binary)."""
    from paddle_tpu.framework import Parameter

    w = _Writer()
    w.parts.append(MAGIC)
    w.u32(VERSION)
    w.u64(int(program.random_seed))
    w.u32(len(program.blocks))
    for block in program.blocks:
        w.i32(block.idx)
        w.i32(block.parent_idx)
        w.i32(getattr(block, "forward_block_idx", -1))
        w.u32(len(block.vars))
        for name in sorted(block.vars):
            v = block.vars[name]
            w.s(v.name)
            w.s(v.type)
            dtype = v.dtype
            w.u8(1 if dtype is not None else 0)
            if dtype is not None:
                w.s(str(dtype))
            shape = v.shape
            w.u8(1 if shape is not None else 0)
            if shape is not None:
                w.u32(len(shape))
                for d in shape:
                    w.i64(int(d))
            w.u32(int(v.lod_level or 0))
            flags = (
                (1 if v.persistable else 0)
                | (2 if v.stop_gradient else 0)
                | (4 if getattr(v, "is_data", False) else 0)
                | (8 if isinstance(v, Parameter) else 0)
                | (16 if getattr(v, "trainable", False) else 0)
            )
            w.u8(flags)
        w.u32(len(block.ops))
        for op in block.ops:
            w.s(op.type)
            for io in (op.inputs, op.outputs):
                w.u32(len(io))
                for slot in sorted(io):
                    w.s(slot)
                    names = io[slot]
                    w.u32(len(names))
                    for n in names:
                        w.s(n if n is not None else "")
            attrs = {k: v for k, v in op.attrs.items()}
            w.u32(len(attrs))
            for name in sorted(attrs):
                w.s(name)
                _write_attr(w, attrs[name])
    return w.bytes()


def deserialize_program(data):
    """bytes -> Program (inverse of serialize_program)."""
    from paddle_tpu.framework import Block, Operator, Parameter, Program

    r = _Reader(data)
    if r.data[:4] != MAGIC:
        raise ValueError("not a PTPB program (bad magic)")
    r.off = 4
    version = r.u32()
    if version != VERSION:
        raise ValueError("unsupported PTPB version %d" % version)
    program = Program()
    program.random_seed = r.u64()
    nblocks = r.u32()
    program.blocks = []
    for _ in range(nblocks):
        idx = r.i32()
        parent = r.i32()
        fwd_idx = r.i32()
        block = Block(program, idx, parent)
        block.forward_block_idx = fwd_idx
        program.blocks.append(block)
        for _ in range(r.u32()):
            name = r.s()
            vtype = r.s()
            dtype = r.s() if r.u8() else None
            shape = None
            if r.u8():
                shape = tuple(r.i64() for _ in range(r.u32()))
            lod_level = r.u32()
            flags = r.u8()
            cls = Parameter if flags & 8 else None
            if cls is Parameter:
                v = Parameter(
                    block, name, shape, dtype,
                    trainable=bool(flags & 16),
                )
            else:
                from paddle_tpu.framework import Variable

                v = Variable(
                    block, name=name, shape=shape, dtype=dtype, type=vtype,
                    lod_level=lod_level,
                )
            v.persistable = bool(flags & 1)
            v.stop_gradient = bool(flags & 2)
            v.is_data = bool(flags & 4)
            block.vars[name] = v
        nops = r.u32()
        for _ in range(nops):
            op_type = r.s()
            ios = []
            for _io in range(2):
                slots = {}
                for _s in range(r.u32()):
                    slot = r.s()
                    slots[slot] = [r.s() for _ in range(r.u32())]
                ios.append(slots)
            attrs = {}
            for _a in range(r.u32()):
                aname = r.s()
                attrs[aname] = _read_attr(r)
            op = Operator.__new__(Operator)
            op.block = block
            op.type = op_type
            op.inputs = ios[0]
            op.outputs = ios[1]
            op.attrs = attrs
            block.ops.append(op)
    program.current_block_idx = 0
    return program
