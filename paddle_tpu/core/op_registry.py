"""Operator registry: schema + JAX lowering + gradient wiring.

Reference parity: ``paddle/fluid/framework/op_registry.h:190`` (registrar
macros), ``op_info.h`` (OpInfoMap), ``grad_op_desc_maker.h:34`` (grad desc
makers), ``op_proto_maker.cc`` (schemas). The TPU-first difference: instead
of registering per-device kernels dispatched one op at a time, each op
registers a *lowering rule* — a pure JAX function — and the Executor traces
a whole block through these rules into a single XLA computation.

Gradients: the default grad maker emits a ``<type>_grad`` op whose lowering
re-traces the forward rule under ``jax.vjp``. Recomputed forward values are
eliminated by XLA CSE inside the fused step program, so this costs nothing
at runtime while keeping Fluid's graph-level autodiff contract (grad ops are
real, inspectable ops that transpilers can rewrite).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.types import canonical_dtype


class LowerContext(object):
    """Per-op context handed to lowering rules.

    Attributes:
      op: the framework.Operator being lowered (desc access).
      is_test: inference mode flag (clone(for_test=True) programs).
      block_lowerer: the BlockLowerer driving the trace (for control-flow
        mega-ops that need to lower sub-blocks).
    """

    def __init__(self, op, rng, is_test=False, block_lowerer=None):
        self.op = op
        self._rng = rng
        self.is_test = is_test
        self.block_lowerer = block_lowerer

    def rng(self):
        """A fresh PRNG key for this op instance (dropout, random init...).

        Deterministic given (program seed, op index); ops with a nonzero
        ``seed`` attr get a key derived from that seed instead, matching the
        reference's per-op seed semantics (e.g. dropout_op.cc seed attr).
        """
        return self._rng()


class OpDef(object):
    __slots__ = (
        "type",
        "inputs",
        "outputs",
        "attrs",
        "lower",
        "grad",
        "no_grad_inputs",
        "intermediate_outputs",
        "infer_shape",
    )

    def __init__(
        self,
        type,
        inputs,
        outputs,
        attrs,
        lower,
        grad,
        no_grad_inputs,
        intermediate_outputs,
        infer_shape,
    ):
        self.type = type
        self.inputs = inputs  # list of slot names; "*X" marks duplicable
        self.outputs = outputs
        self.attrs = attrs  # dict name -> default
        self.lower = lower  # fn(ctx, ins, attrs) -> dict slot -> value(s)
        self.grad = grad  # None | "auto" | callable grad-desc maker
        self.no_grad_inputs = no_grad_inputs
        self.intermediate_outputs = intermediate_outputs
        self.infer_shape = infer_shape  # optional override

    def input_slots(self):
        return [s.lstrip("*") for s in self.inputs]

    def output_slots(self):
        return [s.lstrip("*") for s in self.outputs]

    def is_duplicable_input(self, slot):
        return ("*" + slot) in self.inputs

    def is_duplicable_output(self, slot):
        return ("*" + slot) in self.outputs


_REGISTRY = {}


def register_op(
    type,
    inputs,
    outputs,
    attrs=None,
    lower=None,
    grad="auto",
    no_grad_inputs=(),
    intermediate_outputs=(),
    infer_shape=None,
):
    """Register an operator definition (REGISTER_OPERATOR analog).

    ``inputs``/``outputs``: slot names; prefix with ``*`` for duplicable
    slots (lists of vars, e.g. sum's X). ``grad``:
      - "auto": a generic ``<type>_grad`` op is synthesized whose lowering
        runs jax.vjp over this op's ``lower``;
      - callable(op, out_grads, in_grads_wanted) -> list of op spec dicts:
        custom grad-desc maker (for ops composed of other ops);
      - None: op has no gradient (EmptyGradOpMaker).
    """
    if type in _REGISTRY:
        raise ValueError("op %r already registered" % type)
    if lower is None:
        raise ValueError("op %r needs a lowering rule" % type)
    opdef = OpDef(
        type=type,
        inputs=list(inputs),
        outputs=list(outputs),
        attrs=dict(attrs or {}),
        lower=lower,
        grad=grad,
        no_grad_inputs=frozenset(no_grad_inputs),
        intermediate_outputs=frozenset(intermediate_outputs),
        infer_shape=infer_shape,
    )
    _REGISTRY[type] = opdef
    return opdef


def get_op_def(type):
    opdef = _REGISTRY.get(type)
    if opdef is None:
        raise KeyError("operator %r is not registered" % type)
    return opdef


def has_op(type):
    return type in _REGISTRY


def registered_ops():
    return sorted(_REGISTRY)


def normalize_outputs(opdef, result):
    """Lowerings may return a single array, a tuple (positional outputs), or
    a dict slot -> array|list. Normalize to dict slot -> list[array]."""
    slots = opdef.output_slots()
    if isinstance(result, dict):
        out = {}
        for k, v in result.items():
            out[k] = list(v) if isinstance(v, (list, tuple)) else [v]
        return out
    if isinstance(result, tuple):
        if len(result) != len(slots):
            raise ValueError(
                "op %s lowering returned %d outputs, schema has %d"
                % (opdef.type, len(result), len(slots))
            )
        return {s: [r] for s, r in zip(slots, result)}
    return {slots[0]: [result]}


# ---------------------------------------------------------------------------
# Generic vjp-based gradient lowering
# ---------------------------------------------------------------------------


def lower_grad_via_vjp(fwd_def, ctx, ins, attrs, out_grads, wanted_input_grads):
    """Lower a ``<type>_grad`` op by differentiating the forward lowering.

    ins: forward inputs, dict slot -> list[array].
    out_grads: dict fwd-output-slot -> list[array or None] (None = no
      incoming gradient for that output; treated as zeros).
    wanted_input_grads: dict fwd-input-slot -> list[bool].

    Returns dict fwd-input-slot -> list[array or None].
    """
    import numpy as np

    def _is_inexact_array(a):
        # Composite values (tensor arrays = (buffer, size) tuples) are not
        # differentiable leaves themselves. Checked structurally:
        # jnp.result_type over a tuple PROMOTES instead of raising.
        if isinstance(a, (tuple, list)):
            return False
        try:
            return jnp.issubdtype(jnp.result_type(a), jnp.inexact)
        except TypeError:
            return False

    # Differentiable leaves: wanted AND inexact-dtyped.
    diff_index = []  # (slot, i)
    for slot, arrs in ins.items():
        wants = wanted_input_grads.get(slot, [False] * len(arrs))
        for i, a in enumerate(arrs):
            if i < len(wants) and wants[i] and _is_inexact_array(a):
                diff_index.append((slot, i))

    if not diff_index:
        return {}

    def fwd_fn(*diff_args):
        local = {s: list(v) for s, v in ins.items()}
        for (slot, i), a in zip(diff_index, diff_args):
            local[slot][i] = a
        # Output pytree: dict slot -> list of arrays.
        return normalize_outputs(fwd_def, fwd_def.lower(ctx, local, attrs))

    # memory_optimize: recompute this op's forward inside the backward
    # (jax.checkpoint) instead of letting XLA CSE share stored activations
    # with the forward pass — FLOPs for peak HBM.
    program = ctx.op.block.program
    if getattr(program, "_remat", False) or _flag_remat():
        skip = getattr(program, "_remat_skip", ())
        # skip_opt_set holds forward var names; they appear among the grad
        # op's inputs (forward ins/outs are replayed into it).
        if not (skip and set(ctx.op.input_arg_names()) & set(skip)):
            fwd_fn = jax.checkpoint(fwd_fn)

    primals = tuple(ins[slot][i] for slot, i in diff_index)
    out_tree, vjp_fn = jax.vjp(fwd_fn, *primals)

    def _zero_cot(ref):
        # Composite refs (tensor arrays): zero cotangent per leaf.
        def per_leaf(r):
            rd = jnp.result_type(r)
            if jnp.issubdtype(rd, jnp.inexact):
                return jnp.zeros(jnp.shape(r), rd)
            return np.zeros(jnp.shape(r), jax.dtypes.float0)

        return jax.tree.map(per_leaf, ref)

    # Cotangent pytree mirroring out_tree's structure.
    cot = {}
    for oslot, refs in out_tree.items():
        gs = out_grads.get(oslot, [])
        slot_cot = []
        for j, ref in enumerate(refs):
            if isinstance(ref, (tuple, list)):
                # composite (tensor-array) output: zero cotangent per
                # leaf — result_type would silently promote the tuple
                slot_cot.append(_zero_cot(ref))
                continue
            try:
                rdtype = jnp.result_type(ref)
            except TypeError:
                slot_cot.append(_zero_cot(ref))
                continue
            if not jnp.issubdtype(rdtype, jnp.inexact):
                slot_cot.append(np.zeros(jnp.shape(ref), jax.dtypes.float0))
                continue
            g = gs[j] if j < len(gs) else None
            if g is None:
                g = jnp.zeros(jnp.shape(ref), rdtype)
            else:
                g = jnp.asarray(g, rdtype)
                if jnp.shape(g) != jnp.shape(ref):
                    g = jnp.reshape(g, jnp.shape(ref))
            slot_cot.append(g)
        cot[oslot] = slot_cot
    grads = vjp_fn(cot)

    result = {}
    for (slot, i), g in zip(diff_index, grads):
        result.setdefault(slot, {})[i] = g
    out = {}
    for slot, arrs in ins.items():
        if slot in result:
            out[slot] = [result[slot].get(i) for i in range(len(arrs))]
    return out


def ensure_auto_grad_op(fwd_type):
    """Register (once) the synthesized ``<type>_grad`` operator whose
    lowering differentiates the forward rule. GradOpDescMaker analog."""
    gtype = fwd_type + "_grad"
    if gtype in _REGISTRY:
        return _REGISTRY[gtype]
    fwd = get_op_def(fwd_type)
    if fwd.grad is None:
        raise ValueError("op %r has no gradient" % fwd_type)

    g_inputs = list(fwd.inputs)
    for s in fwd.outputs:
        g_inputs.append(s)
        star = "*" if s.startswith("*") else ""
        g_inputs.append(star + s.lstrip("*") + "@GRAD")
    g_outputs = [
        ("*" if s.startswith("*") else "") + s.lstrip("*") + "@GRAD"
        for s in fwd.inputs
    ]

    def lower(ctx, ins, attrs):
        op = ctx.op
        fwd_ins = {s: ins[s] for s in fwd.input_slots() if s in ins}
        out_grads = {
            o: ins[o + "@GRAD"]
            for o in fwd.output_slots()
            if (o + "@GRAD") in ins
        }
        wanted = {}
        for s in fwd.input_slots():
            names = op.output(s + "@GRAD")
            if any(names):
                wanted[s] = [bool(n) for n in names]
        gres = lower_grad_via_vjp(fwd, ctx, fwd_ins, attrs, out_grads, wanted)
        return {s + "@GRAD": gs for s, gs in gres.items()}

    return register_op(
        gtype, inputs=g_inputs, outputs=g_outputs, lower=lower, grad=None
    )


def assert_dtype(x, dtype):
    return jnp.asarray(x, canonical_dtype(dtype))


def _flag_remat():
    try:
        from paddle_tpu import flags

        return flags.get("remat_gradients")
    except Exception:
        return False
