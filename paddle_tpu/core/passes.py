"""Program-pass framework: registry + PassManager over Program graphs.

Reference parity: paddle/fluid/framework/ir/ (Pass base + REGISTER_PASS,
pass_builder) and the analysis layer that AnalysisPredictor drives. The
TPU-first difference in *scope*: XLA already performs the kernel-level
fusions the reference's mkldnn/ir passes hand-write (conv+relu,
conv+eltwise), so passes here operate at PROGRAM level — semantic
rewrites XLA cannot do on its own (precision policy, BN folding for
serialization, graph slicing, dead-op cleanup) — and the heavy
per-op fusion stays the compiler's job.

A pass is ``fn(program, scope=None, **kwargs) -> program`` (in-place or
returning a new Program). Register with :func:`register_pass`; run with
:class:`PassManager` or :func:`apply_pass`.
"""

import inspect
import logging

logger = logging.getLogger("paddle_tpu.passes")

_PASSES = {}

__all__ = ["register_pass", "get_pass", "list_passes", "apply_pass",
           "PassManager"]


def register_pass(name, fn=None):
    """REGISTER_PASS analog; usable as a decorator."""

    def deco(f):
        if name in _PASSES:
            raise ValueError("pass %r already registered" % name)
        _PASSES[name] = f
        return f

    return deco(fn) if fn is not None else deco


def get_pass(name):
    if name not in _PASSES:
        raise KeyError(
            "unknown pass %r (have: %s)" % (name, ", ".join(sorted(_PASSES)))
        )
    return _PASSES[name]


def list_passes():
    return sorted(_PASSES)


def apply_pass(program, name, scope=None, **kwargs):
    logger.debug("applying pass %s", name)
    fn = get_pass(name)
    # pipelines broadcast kwargs; hand each pass only what it accepts
    sig = inspect.signature(fn)
    if not any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values()):
        kwargs = {k: v for k, v in kwargs.items() if k in sig.parameters}
    out = fn(program, scope=scope, **kwargs)
    return out if out is not None else program


class PassManager(object):
    """Ordered pass pipeline (pass_builder role). ``strategies`` maps a
    use case to a default pipeline, as AnalysisPredictor's pass lists do."""

    STRATEGIES = {
        # deploy: slice to the inference subgraph FIRST (so training-only
        # ops from a clone-after-minimize program can't block fusion
        # conditions), fold BN into convs, fuse fc->recurrence projections
        # (before fc_fuse, which would otherwise claim those mul+add
        # chains as plain fc ops — the reference analyzer orders its pass
        # list the same way), then collapse mul+add(+act) chains into fc
        "inference": ["prune_feed_fetch", "fuse_batch_norm",
                      "fc_lstm_fuse", "embedding_fc_lstm_fuse",
                      "fc_gru_fuse", "seqconv_eltadd_relu_fuse",
                      "fc_fuse"],
        # training memory: rematerialization planning
        "memory": ["memory_optimize"],
        # mixed precision training
        "amp_bf16": ["amp_rewrite"],
    }

    def __init__(self, passes=None, strategy=None):
        if strategy is not None:
            passes = self.STRATEGIES[strategy] + list(passes or [])
        self.passes = list(passes or [])
        for p in self.passes:
            get_pass(p)  # fail fast on unknown names

    def apply(self, program, scope=None, **kwargs):
        for name in self.passes:
            program = apply_pass(program, name, scope=scope, **kwargs)
        return program


# -- built-in passes wrapping the program transforms ------------------------


@register_pass("fuse_batch_norm")
def _fuse_batch_norm(program, scope=None, **kwargs):
    """conv(+bias)+batch_norm fold (ConvBNFusePass / inference
    transpiler role)."""
    from paddle_tpu.transpiler.inference_transpiler import (
        InferenceTranspiler,
    )

    return InferenceTranspiler().transpile(program, scope=scope)


@register_pass("amp_rewrite")
def _amp_rewrite(program, scope=None, dtype="bfloat16", **kwargs):
    """bf16 mixed-precision policy (float16_transpiler role)."""
    from paddle_tpu.transpiler import rewrite_program_amp

    rewrite_program_amp(program, dtype)
    return program


@register_pass("memory_optimize")
def _memory_optimize(program, scope=None, **kwargs):
    """Rematerialization planning (memory_optimize transpiler)."""
    from paddle_tpu.transpiler import memory_optimize

    memory_optimize(program)
    return program


@register_pass("prune_feed_fetch")
def _prune_feed_fetch(program, scope=None, feed_names=None,
                      fetch_names=None, **kwargs):
    """Backward slice to the feed->fetch subgraph (framework/prune.cc).
    No-op unless both name lists are given."""
    if not feed_names or not fetch_names:
        return program
    from paddle_tpu.io import prune_program

    return prune_program(program, feed_names, fetch_names)


def _persistable(block, name):
    v = block.vars.get(name)
    return v is not None and getattr(v, "persistable", False)


def _chain_clear(block, protected, pairs):
    """Shared fusion-chain safety rule: every intermediate var must feed
    ONLY the next op in the chain and never be a feed/fetch target.
    ``pairs`` = [(var_name, expected_consumer_index), ...]."""
    from paddle_tpu.core.graph_pattern import consumers

    for var_name, consumer_idx in pairs:
        if var_name in protected:
            return False
        if [i for i, _, _ in consumers(block, var_name)] != [consumer_idx]:
            return False
    return True


def _projection_safe(block, mul_op, add_op, bias_name):
    """The fused lowerings compute a plain 2-D matmul + trailing-axis
    bias broadcast; reject mul/add attr combinations that mean something
    else (the reference fc_fuse_pass's bias-shape checks)."""
    if mul_op.attrs.get("y_num_col_dims", 1) != 1:
        return False
    if add_op is None:
        return True
    bvar = block.vars.get(bias_name)
    if bvar is None or len(getattr(bvar, "shape", ()) or ()) != 1:
        return False
    xn = mul_op.attrs.get("x_num_col_dims", 1)
    return add_op.attrs.get("axis", -1) in (-1, xn)


@register_pass("fc_fuse")
def _fc_fuse(program, scope=None, feed_names=None, fetch_names=None,
             **kwargs):
    """Collapse mul + elementwise_add(persistable bias) [+ activation]
    chains into single ``fc`` ops (fc_fuse_pass.cc role). Applied to
    inference programs: intermediates consumed by grad ops (training
    graphs) fail the single-consumer condition and are left alone.
    Vars named in feed_names/fetch_names are never deleted or absorbed."""
    from paddle_tpu.core.graph_pattern import GraphPatternDetector

    protected = set(feed_names or ()) | set(fetch_names or ())

    def _rewrite(block, m, with_act):
        if not (_persistable(block, m.var("w"))
                and _persistable(block, m.var("b"))):
            return False
        mul_op, add_op = m.op("mul"), m.op("add")
        xn = mul_op.attrs.get("x_num_col_dims", 1)
        if not _projection_safe(block, mul_op, add_op, m.var("b")):
            return False
        pairs = [(m.var("mid"), m.op_index("add"))]
        if with_act:
            pairs.append((m.var("out"), m.op_index("act")))
        if not _chain_clear(block, protected, pairs):
            return False
        idxs = m.op_indices()
        final = m.var("final") if with_act else m.var("out")
        attrs = {
            "in_num_col_dims": xn,
            "activation_type": m.op("act").type if with_act else "",
        }
        for i in reversed(idxs):
            block.remove_op(i)
        block.insert_op(
            idxs[0], "fc",
            inputs={"Input": [m.var("x")], "W": [m.var("w")],
                    "Bias": [m.var("b")]},
            outputs={"Out": [final]},
            attrs=attrs)
        block.vars.pop(m.var("mid"), None)
        if with_act:
            block.vars.pop(m.var("out"), None)
        return True

    for bi in range(program.num_blocks):
        block = program.block(bi)
        # longest chain first so mul+add+act doesn't half-match; within a
        # wave, rewrite bottom-up so earlier matches' indices stay valid
        for with_act in (True, False):
            changed = True
            while changed:
                changed = False
                pat = GraphPatternDetector()
                pat.op("mul", "mul",
                       inputs={"X": "x", "Y": "w"}, outputs={"Out": "mid"})
                pat.op("add", "elementwise_add",
                       inputs={"X": "mid", "Y": "b"}, outputs={"Out": "out"})
                if with_act:
                    pat.op("act", ("relu", "tanh", "sigmoid", "gelu"),
                           inputs={"X": "out"}, outputs={"Out": "final"})
                matches = pat.detect(block)
                for m in sorted(matches, key=lambda m: -m.op_indices()[0]):
                    if not m.is_live(block):
                        changed = True  # shifted by an earlier rewrite in
                        continue        # this wave; next wave retries it
                    changed |= _rewrite(block, m, with_act)
    program._bump_version()
    return program


def _fc_rnn_fuse(program, rnn_type, fused_type, feed_names, fetch_names):
    """Shared body of fc_lstm_fuse / fc_gru_fuse (fc_lstm_fuse_pass.cc,
    fc_gru_fuse_pass.cc roles): collapse the projection fc feeding a
    recurrence into one fusion op. Inference-scope, like fc_fuse."""
    from paddle_tpu.core.graph_pattern import GraphPatternDetector

    protected = set(feed_names or ()) | set(fetch_names or ())

    for bi in range(program.num_blocks):
        block = program.block(bi)
        for with_bias in (True, False):
            changed = True
            while changed:
                changed = False
                pat = GraphPatternDetector()
                pat.op("mul", "mul",
                       inputs={"X": "x", "Y": "wx"}, outputs={"Out": "mid"})
                rnn_in = "mid"
                if with_bias:
                    pat.op("add", "elementwise_add",
                           inputs={"X": "mid", "Y": "bx"},
                           outputs={"Out": "proj"})
                    rnn_in = "proj"
                pat.op("rnn", rnn_type, inputs={"Input": rnn_in})
                for m in sorted(pat.detect(block),
                                key=lambda mm: -mm.op_indices()[0]):
                    if not m.is_live(block):
                        changed = True
                        continue
                    if not _persistable(block, m.var("wx")):
                        continue
                    if with_bias and not _persistable(block, m.var("bx")):
                        continue
                    if not _projection_safe(
                            block, m.op("mul"),
                            m.op("add") if with_bias else None,
                            m.var("bx") if with_bias else None):
                        continue
                    pairs = [(m.var("mid"), m.op_index("add") if with_bias
                              else m.op_index("rnn"))]
                    if with_bias:
                        pairs.append((m.var("proj"), m.op_index("rnn")))
                    if not _chain_clear(block, protected, pairs):
                        continue
                    rnn = m.op("rnn")
                    inputs = {"X": [m.var("x")], "WeightX": [m.var("wx")],
                              "WeightH": rnn.input("Weight")}
                    if with_bias:
                        inputs["BiasX"] = [m.var("bx")]
                    for slot in ("Bias", "H0", "C0", "Length"):
                        if rnn.input(slot):
                            inputs[slot] = rnn.input(slot)
                    idxs = m.op_indices()
                    for i in reversed(idxs):
                        block.remove_op(i)
                    # insert at the RECURRENCE's (shifted) position, not
                    # the mul's: ops between them may produce the rnn's
                    # H0/C0/Length inputs, which must stay upstream
                    at = m.op_index("rnn") - (len(idxs) - 1)
                    block.insert_op(
                        at, fused_type,
                        inputs=inputs,
                        outputs=dict(rnn.outputs),
                        # plain attr copy carries op_role/op_role_var too
                        attrs={k: v for k, v in rnn.attrs.items()
                               if not k.startswith("__")})
                    for var_name, _ in pairs:
                        block.vars.pop(var_name, None)
                    changed = True
    program._bump_version()
    return program


@register_pass("fc_lstm_fuse")
def _fc_lstm_fuse(program, scope=None, feed_names=None, fetch_names=None,
                  **kwargs):
    """mul(+bias) feeding dynamic_lstm -> fusion_lstm."""
    return _fc_rnn_fuse(program, "dynamic_lstm", "fusion_lstm",
                        feed_names, fetch_names)


@register_pass("fc_gru_fuse")
def _fc_gru_fuse(program, scope=None, feed_names=None, fetch_names=None,
                 **kwargs):
    """mul(+bias) feeding dynamic_gru -> fusion_gru."""
    return _fc_rnn_fuse(program, "dynamic_gru", "fusion_gru",
                        feed_names, fetch_names)


@register_pass("embedding_fc_lstm_fuse")
def _embedding_fc_lstm_fuse(program, scope=None, feed_names=None,
                            fetch_names=None, **kwargs):
    """lookup_table feeding a fusion_lstm -> fused_embedding_fc_lstm
    (embedding_fc_lstm_fuse_pass.cc role). Run AFTER fc_lstm_fuse, which
    builds the fusion_lstm this pass extends by one hop."""
    from paddle_tpu.core.graph_pattern import GraphPatternDetector

    protected = set(feed_names or ()) | set(fetch_names or ())
    for bi in range(program.num_blocks):
        block = program.block(bi)
        changed = True
        while changed:
            changed = False
            pat = GraphPatternDetector()
            pat.op("emb", "lookup_table",
                   inputs={"W": "table", "Ids": "ids"},
                   outputs={"Out": "mid"})
            pat.op("lstm", "fusion_lstm", inputs={"X": "mid"})
            for m in sorted(pat.detect(block),
                            key=lambda mm: -mm.op_indices()[0]):
                if not m.is_live(block):
                    changed = True
                    continue
                if not _persistable(block, m.var("table")):
                    continue
                if not _chain_clear(block, protected,
                                    [(m.var("mid"), m.op_index("lstm"))]):
                    continue
                lstm = m.op("lstm")
                inputs = dict(lstm.inputs)
                inputs.pop("X", None)
                inputs["Ids"] = [m.var("ids")]
                inputs["Embeddings"] = [m.var("table")]
                attrs = {k: v for k, v in lstm.attrs.items()
                         if not k.startswith("__")}
                attrs["padding_idx"] = m.op("emb").attrs.get(
                    "padding_idx", -1)
                idxs = m.op_indices()
                for i in reversed(idxs):
                    block.remove_op(i)
                at = m.op_index("lstm") - (len(idxs) - 1)
                block.insert_op(at, "fused_embedding_fc_lstm",
                                inputs=inputs,
                                outputs=dict(lstm.outputs), attrs=attrs)
                block.vars.pop(m.var("mid"), None)
                changed = True
    program._bump_version()
    return program


@register_pass("seqconv_eltadd_relu_fuse")
def _seqconv_eltadd_relu_fuse(program, scope=None, feed_names=None,
                              fetch_names=None, **kwargs):
    """sequence_conv + elementwise_add(persistable bias) + relu ->
    fusion_seqconv_eltadd_relu (fuse_pass role of the same name)."""
    from paddle_tpu.core.graph_pattern import GraphPatternDetector

    protected = set(feed_names or ()) | set(fetch_names or ())
    for bi in range(program.num_blocks):
        block = program.block(bi)
        changed = True
        while changed:
            changed = False
            pat = GraphPatternDetector()
            pat.op("conv", "sequence_conv", outputs={"Out": "mid"})
            pat.op("add", "elementwise_add",
                   inputs={"X": "mid", "Y": "b"}, outputs={"Out": "mid2"})
            pat.op("relu", "relu", inputs={"X": "mid2"},
                   outputs={"Out": "out"})
            for m in sorted(pat.detect(block),
                            key=lambda mm: -mm.op_indices()[0]):
                if not m.is_live(block):
                    changed = True
                    continue
                if not _persistable(block, m.var("b")):
                    continue
                bvar = block.vars.get(m.var("b"))
                if len(getattr(bvar, "shape", ()) or ()) != 1:
                    continue
                if m.op("add").attrs.get("axis", -1) not in (-1, 2):
                    continue
                if not _chain_clear(block, protected, [
                        (m.var("mid"), m.op_index("add")),
                        (m.var("mid2"), m.op_index("relu"))]):
                    continue
                conv = m.op("conv")
                inputs = dict(conv.inputs)
                inputs["Bias"] = [m.var("b")]
                attrs = {k: v for k, v in conv.attrs.items()
                         if not k.startswith("__")}
                idxs = m.op_indices()
                for i in reversed(idxs):
                    block.remove_op(i)
                block.insert_op(idxs[0], "fusion_seqconv_eltadd_relu",
                                inputs=inputs,
                                outputs={"Out": [m.var("out")]},
                                attrs=attrs)
                for label in ("mid", "mid2"):
                    block.vars.pop(m.var(label), None)
                changed = True
    program._bump_version()
    return program


@register_pass("fuse_elewise_add_act")
def _fuse_elewise_add_act(program, scope=None, **kwargs):
    """elementwise_add + activation -> fused_elemwise_activation
    (fuse_elewise_add_act_pass.cc role), on forward AND backward ops.

    The fused op also exports the sum as IntermediateOut under the add
    output's original name, so any other consumer (metrics, fetches,
    grad-op forward replays) keeps resolving. The matching grad pair
    act_grad + elementwise_add_grad is collapsed into one synthesized
    fused_elemwise_activation_grad when the intermediate gradient flows
    nowhere else."""
    from paddle_tpu.core.graph_pattern import GraphPatternDetector

    acts = ("relu", "tanh", "sigmoid", "gelu")
    for bi in range(program.num_blocks):
        block = program.block(bi)
        changed = True
        while changed:
            changed = False
            pat = GraphPatternDetector()
            pat.op("add", "elementwise_add",
                   inputs={"X": "x", "Y": "y"}, outputs={"Out": "mid"})
            pat.op("act", acts, inputs={"X": "mid"}, outputs={"Out": "out"})
            # apply the whole disjoint wave bottom-up (earlier matches'
            # indices survive later-in-block rewrites), then re-detect
            # once for cascades
            for m in sorted(pat.detect(block),
                            key=lambda m: -m.op_indices()[0]):
                if not m.is_live(block):
                    # an earlier rewrite in this wave shifted this match's
                    # indices (interleaved chains); the next wave's fresh
                    # detect() will retry it
                    changed = True
                    continue
                act_type = m.op("act").type
                add_op = m.op("add")
                axis = add_op.attrs.get("axis", -1)
                i_add, i_act = m.op_index("add"), m.op_index("act")
                for i in sorted((i_add, i_act), reverse=True):
                    block.remove_op(i)
                block.insert_op(
                    i_add, "fused_elemwise_activation",
                    inputs={"X": [m.var("x")], "Y": [m.var("y")]},
                    outputs={"Out": [m.var("out")],
                             "IntermediateOut": [m.var("mid")]},
                    attrs=dict(
                        _role_attrs(add_op),
                        functor_list=["elementwise_add", act_type],
                        axis=axis, save_intermediate_out=True))
                _fuse_add_act_grad_pair(block, m, act_type, axis)
                changed = True
    program._bump_version()
    return program


def _role_attrs(src_op):
    """OpRole (+role-var) attrs carried from a replaced op onto its fused
    replacement, so role-keyed passes (pipeline cut, gradient merge,
    distribute transpiler) keep classifying the op correctly."""
    from paddle_tpu.framework import OP_ROLE_ATTR_NAME, OP_ROLE_VAR_ATTR_NAME

    out = {}
    for k in (OP_ROLE_ATTR_NAME, OP_ROLE_VAR_ATTR_NAME):
        if k in src_op.attrs:
            out[k] = src_op.attrs[k]
    return out


def _fuse_add_act_grad_pair(block, m, act_type, axis):
    """Collapse the backward twin of a fused add+act pair, if present."""
    from paddle_tpu.core.graph_pattern import (
        GraphPatternDetector,
        consumers,
    )
    from paddle_tpu.core.op_registry import ensure_auto_grad_op

    gpat = GraphPatternDetector()
    gpat.op("act_grad", act_type + "_grad",
            inputs={"X": "mid", "Out@GRAD": "dout"},
            outputs={"X@GRAD": "dmid"})
    gpat.op("add_grad", "elementwise_add_grad",
            inputs={"X": "x", "Y": "y", "Out@GRAD": "dmid"})
    for gm in gpat.detect(block):
        if (gm.var("mid") != m.var("mid") or gm.var("x") != m.var("x")
                or gm.var("y") != m.var("y")):
            continue
        # the intermediate gradient must flow nowhere else
        dmid = gm.var("dmid")
        users = [i for i, _, _ in consumers(block, dmid)]
        if users != [gm.op_index("add_grad")]:
            continue
        add_g = gm.op("add_grad")
        dx = add_g.output("X@GRAD")
        dy = add_g.output("Y@GRAD")
        ensure_auto_grad_op("fused_elemwise_activation")
        i_ag, i_eg = gm.op_index("act_grad"), gm.op_index("add_grad")
        for i in sorted((i_ag, i_eg), reverse=True):
            block.remove_op(i)
        outputs = {}
        if any(dx):
            outputs["X@GRAD"] = dx
        if any(dy):
            outputs["Y@GRAD"] = dy
        block.insert_op(
            i_ag, "fused_elemwise_activation_grad",
            inputs={"X": [gm.var("x")], "Y": [gm.var("y")],
                    "Out": [m.var("out")], "Out@GRAD": [gm.var("dout")]},
            outputs=outputs,
            attrs=dict(_role_attrs(add_g),
                       functor_list=["elementwise_add", act_type],
                       axis=axis, save_intermediate_out=True))
        block.vars.pop(dmid, None)
        return


@register_pass("delete_dropout")
def _delete_dropout(program, scope=None, **kwargs):
    """Neutralize inference-mode dropout (identity at is_test with
    upscale_in_train), in every block. Downstream readers are rewired to
    the dropout input; because the pass cannot know what a future
    exe.run will fetch, the op itself is downgraded to an ``assign``
    (XLA elides the copy) rather than removed, so fetching the old
    output name keeps working. The dead Mask var is dropped."""
    for bi in range(program.num_blocks):
        block = program.block(bi)
        for i, op in enumerate(block.ops):
            if not (
                op.type == "dropout"
                and op.attrs.get("is_test", False)
                and op.attrs.get("dropout_implementation")
                == "upscale_in_train"
            ):
                continue
            src = op.input("X")[0]
            dst = op.output("Out")[0]
            for mask in op.output("Mask"):
                block.vars.pop(mask, None)
            for later in block.ops[i + 1:]:
                for slot, names in list(later.inputs.items()):
                    later.inputs[slot] = [
                        src if n == dst else n for n in names
                    ]
            op.type = "assign"
            op.inputs = {"X": [src]}
            op.outputs = {"Out": [dst]}
    program._bump_version()
    return program
