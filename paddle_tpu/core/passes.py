"""Program-pass framework: registry + PassManager over Program graphs.

Reference parity: paddle/fluid/framework/ir/ (Pass base + REGISTER_PASS,
pass_builder) and the analysis layer that AnalysisPredictor drives. The
TPU-first difference in *scope*: XLA already performs the kernel-level
fusions the reference's mkldnn/ir passes hand-write (conv+relu,
conv+eltwise), so passes here operate at PROGRAM level — semantic
rewrites XLA cannot do on its own (precision policy, BN folding for
serialization, graph slicing, dead-op cleanup) — and the heavy
per-op fusion stays the compiler's job.

A pass is ``fn(program, scope=None, **kwargs) -> program`` (in-place or
returning a new Program). Register with :func:`register_pass`; run with
:class:`PassManager` or :func:`apply_pass`.
"""

import inspect
import logging

logger = logging.getLogger("paddle_tpu.passes")

_PASSES = {}

__all__ = ["register_pass", "get_pass", "list_passes", "apply_pass",
           "PassManager"]


def register_pass(name, fn=None):
    """REGISTER_PASS analog; usable as a decorator."""

    def deco(f):
        if name in _PASSES:
            raise ValueError("pass %r already registered" % name)
        _PASSES[name] = f
        return f

    return deco(fn) if fn is not None else deco


def get_pass(name):
    if name not in _PASSES:
        raise KeyError(
            "unknown pass %r (have: %s)" % (name, ", ".join(sorted(_PASSES)))
        )
    return _PASSES[name]


def list_passes():
    return sorted(_PASSES)


def apply_pass(program, name, scope=None, **kwargs):
    logger.debug("applying pass %s", name)
    fn = get_pass(name)
    # pipelines broadcast kwargs; hand each pass only what it accepts
    sig = inspect.signature(fn)
    if not any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values()):
        kwargs = {k: v for k, v in kwargs.items() if k in sig.parameters}
    out = fn(program, scope=scope, **kwargs)
    return out if out is not None else program


class PassManager(object):
    """Ordered pass pipeline (pass_builder role). ``strategies`` maps a
    use case to a default pipeline, as AnalysisPredictor's pass lists do."""

    STRATEGIES = {
        # deploy: fold BN into convs, slice to the inference subgraph
        "inference": ["fuse_batch_norm", "prune_feed_fetch"],
        # training memory: rematerialization planning
        "memory": ["memory_optimize"],
        # mixed precision training
        "amp_bf16": ["amp_rewrite"],
    }

    def __init__(self, passes=None, strategy=None):
        if strategy is not None:
            passes = self.STRATEGIES[strategy] + list(passes or [])
        self.passes = list(passes or [])
        for p in self.passes:
            get_pass(p)  # fail fast on unknown names

    def apply(self, program, scope=None, **kwargs):
        for name in self.passes:
            program = apply_pass(program, name, scope=scope, **kwargs)
        return program


# -- built-in passes wrapping the program transforms ------------------------


@register_pass("fuse_batch_norm")
def _fuse_batch_norm(program, scope=None, **kwargs):
    """conv(+bias)+batch_norm fold (ConvBNFusePass / inference
    transpiler role)."""
    from paddle_tpu.transpiler.inference_transpiler import (
        InferenceTranspiler,
    )

    return InferenceTranspiler().transpile(program, scope=scope)


@register_pass("amp_rewrite")
def _amp_rewrite(program, scope=None, dtype="bfloat16", **kwargs):
    """bf16 mixed-precision policy (float16_transpiler role)."""
    from paddle_tpu.transpiler import rewrite_program_amp

    rewrite_program_amp(program, dtype)
    return program


@register_pass("memory_optimize")
def _memory_optimize(program, scope=None, **kwargs):
    """Rematerialization planning (memory_optimize transpiler)."""
    from paddle_tpu.transpiler import memory_optimize

    memory_optimize(program)
    return program


@register_pass("prune_feed_fetch")
def _prune_feed_fetch(program, scope=None, feed_names=None,
                      fetch_names=None, **kwargs):
    """Backward slice to the feed->fetch subgraph (framework/prune.cc).
    No-op unless both name lists are given."""
    if not feed_names or not fetch_names:
        return program
    from paddle_tpu.io import prune_program

    return prune_program(program, feed_names, fetch_names)


@register_pass("delete_dropout")
def _delete_dropout(program, scope=None, **kwargs):
    """Neutralize inference-mode dropout (identity at is_test with
    upscale_in_train), in every block. Downstream readers are rewired to
    the dropout input; because the pass cannot know what a future
    exe.run will fetch, the op itself is downgraded to an ``assign``
    (XLA elides the copy) rather than removed, so fetching the old
    output name keeps working. The dead Mask var is dropped."""
    for bi in range(program.num_blocks):
        block = program.block(bi)
        for i, op in enumerate(block.ops):
            if not (
                op.type == "dropout"
                and op.attrs.get("is_test", False)
                and op.attrs.get("dropout_implementation")
                == "upscale_in_train"
            ):
                continue
            src = op.input("X")[0]
            dst = op.output("Out")[0]
            for mask in op.output("Mask"):
                block.vars.pop(mask, None)
            for later in block.ops[i + 1:]:
                for slot, names in list(later.inputs.items()):
                    later.inputs[slot] = [
                        src if n == dst else n for n in names
                    ]
            op.type = "assign"
            op.inputs = {"X": [src]}
            op.outputs = {"Out": [dst]}
    program._bump_version()
    return program
