"""Mixed-precision (bf16) program rewrite — the TPU-era analog of the
reference's fp16 inference transpiler (paddle/contrib/float16/
float16_transpiler.py), redesigned for *training*:

- master weights, optimizer state and loss stay float32 in the Scope;
- MXU-bound ops (conv/matmul family) compute in bfloat16: their float32
  inputs are cast to bf16 at the op boundary, so XLA fuses the casts into
  the conv/dot and activations flow bf16 through the network;
- numerically sensitive ops (losses, softmax over logits, optimizer
  updates, norms/metrics) cast bf16 inputs back up to float32.

Because gradients are synthesized by re-tracing forward rules under
jax.vjp (core/op_registry.py), the same boundary casts differentiate
correctly: a ``conv2d_grad`` produces bf16 weight grads, and the optimizer
op's f32 upcast makes the master-weight update exact — no loss scaling is
needed for bf16 (same exponent range as f32).

The pass is applied during block lowering (`BlockLowerer.lower_op`), which
is where program->XLA rewriting happens in this framework; enable it with
``paddle_tpu.transpiler.rewrite_program_amp(prog)`` or the
``paddle_tpu.transpiler.amp_guard`` context manager.
"""

import jax.numpy as jnp

# Ops whose f32 inputs are cast DOWN to the amp dtype: the MXU FLOP sinks
# plus cheap elementwise ops that should not re-promote activations.
WHITE_LIST = frozenset(
    {
        "mul",
        "matmul",
        "conv2d",
        "conv3d",
        "conv2d_transpose",
        "depthwise_conv2d",
        "sequence_conv",
        "attention",  # fused attention lowering (flash kernel)
    }
)

# Ops whose low-precision inputs are cast UP to f32: losses and statistics
# where bf16 mantissa (8 bits) visibly hurts, and every optimizer update
# (master weights must accumulate in f32).
BLACK_LIST = frozenset(
    {
        "softmax_with_cross_entropy",
        "cross_entropy",
        "cross_entropy2",
        "sigmoid_cross_entropy_with_logits",
        "mean",
        "softmax",
        "reduce_mean",
        "reduce_sum",
        "accuracy",
        "auc",
        "layer_norm",
        "l2_normalize",
        "norm",
        "clip_by_norm",
        "squared_l2_norm",
        "linear_chain_crf",
        "warpctc",
        # optimizer ops (ops/optimizer_ops.py)
        "sgd",
        "momentum",
        "lars_momentum",
        "adam",
        "adamax",
        "adagrad",
        "decayed_adagrad",
        "adadelta",
        "rmsprop",
        "ftrl",
        "proximal_gd",
        "proximal_adagrad",
    }
)


def _cast_tree(ins, src_pred, dst):
    out = {}
    changed = False
    for slot, arrs in ins.items():
        res = []
        for a in arrs:
            try:
                dt = jnp.result_type(a)
            except TypeError:
                res.append(a)
                continue
            if src_pred(dt):
                res.append(jnp.asarray(a).astype(dst))
                changed = True
            else:
                res.append(a)
        out[slot] = res
    return out if changed else ins


def apply_amp_casts(op_type, ins, amp_dtype):
    """Cast an op's inputs per the white/black lists. Grad ops follow their
    forward op's class (the vjp re-trace then runs in the same precision)."""
    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    low = jnp.dtype(amp_dtype)
    if base in WHITE_LIST:
        return _cast_tree(ins, lambda dt: dt == jnp.float32, low)
    if base in BLACK_LIST:
        return _cast_tree(ins, lambda dt: dt == low, jnp.float32)
    return ins
