"""Declarative subgraph pattern matching over Program blocks.

Reference parity: ``paddle/fluid/framework/ir/graph_pattern_detector.cc``
(PDPattern/PDNode + GraphPatternDetector) — the engine behind the
reference's fusion passes (fc_fuse_pass.cc, fuse_elewise_add_act_pass.cc,
conv_bn_fuse_pass.cc, ...). The TPU-first difference in scope: XLA already
performs kernel-level fusion, so passes built on this detector do
*semantic* graph surgery (collapsing op chains into registered fused ops,
structural rewrites transpilers need) rather than hand-scheduling kernels.

A pattern is an ordered list of op specs. Edges are expressed by shared
var *labels*: binding the same label to a producer's output slot and a
consumer's input slot constrains the two ops to be connected through one
variable. ``detect`` returns non-overlapping matches in program order.

Example — mul followed by elementwise_add through label "mid"::

    pat = GraphPatternDetector()
    pat.op("mul", "mul", inputs={"X": "x", "Y": "w"}, outputs={"Out": "mid"})
    pat.op("add", "elementwise_add", inputs={"X": "mid", "Y": "b"},
           outputs={"Out": "out"})
    for m in pat.detect(block):
        m.op("mul"), m.op_index("add"), m.var("mid")
"""


class Match(object):
    """One subgraph match: pattern-op-name -> (block op index, Operator),
    var label -> var name."""

    def __init__(self, ops, vars_):
        self._ops = ops  # name -> (index, Operator)
        self._vars = vars_  # label -> var name

    def op(self, name):
        return self._ops[name][1]

    def op_index(self, name):
        return self._ops[name][0]

    def op_indices(self):
        return sorted(i for i, _ in self._ops.values())

    def var(self, label):
        return self._vars[label]

    def is_live(self, block):
        """True while every matched op still sits at its recorded index —
        rewriting passes that apply a whole detect() wave must check this
        per match, since an earlier rewrite shifts later indices (a stale
        match would remove the wrong ops)."""
        ops = block.ops
        return all(
            i < len(ops) and ops[i] is op for i, op in self._ops.values()
        )

    def __repr__(self):
        return "Match(ops=%r, vars=%r)" % (
            {k: v[0] for k, v in self._ops.items()}, self._vars)


class _OpSpec(object):
    __slots__ = ("name", "types", "inputs", "outputs", "cond")

    def __init__(self, name, types, inputs, outputs, cond):
        self.name = name
        self.types = frozenset([types] if isinstance(types, str) else types)
        self.inputs = dict(inputs or {})
        self.outputs = dict(outputs or {})
        self.cond = cond


class GraphPatternDetector(object):
    """Ordered-op-spec pattern + backtracking matcher (PDPattern role)."""

    def __init__(self):
        self._specs = []

    def op(self, name, types, inputs=None, outputs=None, cond=None):
        """Add an op node to the pattern.

        name: handle for retrieving the matched op from a Match.
        types: op type string or iterable of acceptable types.
        inputs/outputs: {slot: var_label}; the first var in the slot is
          bound to the label. Same label across specs = same variable.
        cond: optional predicate fn(Operator) -> bool.
        """
        if any(s.name == name for s in self._specs):
            raise ValueError("pattern op %r already defined" % name)
        self._specs.append(_OpSpec(name, types, inputs, outputs, cond))
        return self

    def detect(self, block, overlapping=False):
        """Match the pattern against ``block.ops``.

        Returns a list of :class:`Match`, anchored on the first spec in
        program order. Unless ``overlapping`` is set, matches are made
        disjoint greedily (two matches never share a block op), which is
        what rewriting passes want.
        """
        specs = self._specs
        if not specs:
            return []
        ops = list(block.ops)
        matches = []
        taken = set()

        def try_bind(spec, op, bound_vars):
            """Bind spec's slot labels against op; None on conflict."""
            binds = {}
            for slots, getter in (
                (spec.inputs, op.input),
                (spec.outputs, op.output),
            ):
                for slot, label in slots.items():
                    names = getter(slot)
                    if not names or not names[0]:
                        return None
                    expect = bound_vars.get(label, binds.get(label))
                    if expect is None:
                        binds[label] = names[0]
                    elif expect != names[0]:
                        return None
            return binds

        def candidate(spec, i, op):
            if op.type not in spec.types:
                return False
            if not overlapping and i in taken:
                return False
            return spec.cond is None or spec.cond(op)

        def backtrack(k, bound_ops, bound_vars, used):
            if k == len(specs):
                return Match(dict(bound_ops), dict(bound_vars))
            spec = specs[k]
            for i, op in enumerate(ops):
                if i in used or not candidate(spec, i, op):
                    continue
                binds = try_bind(spec, op, bound_vars)
                if binds is None:
                    continue
                nv = dict(bound_vars)
                nv.update(binds)
                bound_ops[spec.name] = (i, op)
                m = backtrack(k + 1, bound_ops, nv, used | {i})
                if m is not None:
                    return m
                del bound_ops[spec.name]
            return None

        for i, op in enumerate(ops):
            if not candidate(specs[0], i, op):
                continue
            binds = try_bind(specs[0], op, {})
            if binds is None:
                continue
            m = backtrack(1, {specs[0].name: (i, op)}, binds, {i})
            if m is not None:
                matches.append(m)
                if not overlapping:
                    taken |= set(m.op_indices())
        return matches


def producer(block, var_name):
    """(index, op) of the op writing ``var_name``, or None (prefers the
    LAST writer, matching execution order)."""
    found = None
    for i, op in enumerate(block.ops):
        if var_name in op.output_arg_names():
            found = (i, op)
    return found


def consumers(block, var_name, start=0):
    """All (index, op, slot) reading ``var_name`` at or after ``start``."""
    out = []
    for i, op in enumerate(block.ops):
        if i < start:
            continue
        for slot, names in op.inputs.items():
            if var_name in names:
                out.append((i, op, slot))
    return out
