"""Persistent cross-process executable cache + compile-tax telemetry.

Every process start (serving replica, bench run, CI shard) used to pay the
full XLA compile from scratch. This module kills that tax in two layers,
both keyed off the structural fingerprints in ``core/fingerprint.py`` and
switched by ``FLAGS_exec_cache_dir`` (empty = disabled, zero overhead):

1. **XLA compile cache** (``<dir>/xla``): JAX's persistent compilation
   cache, enabled process-wide. A warm process still re-traces the program
   to HLO, but the backend compile is replaced by a disk load (content
   hash of the HLO module, so it also dedups across Executor instances
   and structurally identical programs).
2. **AOT executable images** (``<dir>/aot``): serialized
   ``lower()``/``compile()`` output of the whole step function, keyed by
   ``fingerprint.executable_key`` x argument avals x jax/jaxlib versions.
   A warm process skips even the trace: the executable deserializes
   straight into a callable.

Corruption/eviction tolerance: every load path catches, counts,
*quarantines* the bad entry (``<aot>/quarantine/`` — moved aside for
autopsy, never re-read) and falls back to a fresh compile — a bad cache
entry can cost time, never correctness, and never a crash.
``FLAGS_exec_cache_max_bytes`` bounds both layers (LRU on the XLA cache,
oldest-mtime trim on AOT files).

TRUST BOUNDARY: AOT images deserialize through pickle, so the cache dir
must be writable only by principals you would let execute code in this
process (dirs are created 0o700; never point the flag at a
world-writable path).

Stats: counters below are exported through ``profiler.exec_cache_stats()``
and feed ``bench.py``'s ``compile_seconds_cold``/``compile_seconds_warm``
fields. Backend compile time is observed via ``jax.monitoring`` events, so
compiles that happen outside this module (stray helper jits) are counted
too — the numbers are the process's whole compile tax, not just the
executor's share.
"""

import hashlib
import os
import pickle
import tempfile
import threading

from paddle_tpu.observability import lock_witness
import time

import jax

_lock = lock_witness.make_lock("core.exec_cache")
_tls = threading.local()

_STAT_KEYS = (
    "trace_cache_hits",      # in-process CompiledProgram reuse (executor)
    "trace_cache_misses",    # CompiledProgram constructions (re-traces)
    "backend_compiles",      # XLA backend compile calls observed
    "persistent_hits",       # backend compiles served from the disk cache
    "persistent_misses",     # backend compiles that ran for real
    "aot_hits",              # whole executables deserialized from disk
    "aot_misses",
    "aot_errors",            # corrupt/incompatible AOT entries tolerated
)

_stats = {k: 0 for k in _STAT_KEYS}
_stats.update(
    compile_seconds=0.0,         # total wall time inside backend compiles
    compile_seconds_cold=0.0,    # ...attributable to fresh compiles
    compile_seconds_warm=0.0,    # ...attributable to cache loads
    cache_retrieval_seconds=0.0,
)

_configured = {"dir": None}


# -- monitoring taps ---------------------------------------------------------
def _on_event(name, **kw):
    if name == "/jax/compilation_cache/compile_requests_use_cache":
        # fires at the start of every cache-consulting compile: clearing
        # here keeps a stale hit/miss verdict from a compile that never
        # emitted its duration event out of the next attribution
        _tls.last = None
    elif name == "/jax/compilation_cache/cache_hits":
        with _lock:
            _stats["persistent_hits"] += 1
        _tls.last = "hit"
    elif name == "/jax/compilation_cache/cache_misses":
        with _lock:
            _stats["persistent_misses"] += 1
        _tls.last = "miss"


def _on_duration(name, secs, **kw):
    if name == "/jax/core/compile/backend_compile_duration":
        # the hit/miss event for THIS compile fired earlier on this same
        # thread (jax records them synchronously inside the compile call),
        # so a thread-local carries the attribution across the two taps
        last = getattr(_tls, "last", None)
        _tls.last = None
        with _lock:
            _stats["backend_compiles"] += 1
            _stats["compile_seconds"] += secs
            if last == "hit":
                _stats["compile_seconds_warm"] += secs
            else:
                _stats["compile_seconds_cold"] += secs
        _record_compile_span("xla_backend_compile", secs,
                             "warm" if last == "hit" else "cold")
    elif name == "/jax/compilation_cache/cache_retrieval_time_sec":
        with _lock:
            _stats["cache_retrieval_seconds"] += secs


def _record_compile_span(name, secs, kind):
    """Land the compile in the profiler's unified trace stream (cat
    ``compile``). The duration event fires at compile END, so the span is
    back-dated by its length; no-op when the profiler is off."""
    try:
        from paddle_tpu import profiler

        if profiler.enabled():
            end = time.perf_counter()
            profiler.record_span(name, end - secs, end, cat="compile",
                                 args={"kind": kind})
    except Exception:
        pass


jax.monitoring.register_event_listener(_on_event)
jax.monitoring.register_event_duration_secs_listener(_on_duration)


def record_trace_hit():
    with _lock:
        _stats["trace_cache_hits"] += 1


def record_trace_miss():
    with _lock:
        _stats["trace_cache_misses"] += 1


def stats():
    """Snapshot of the cache counters. ``fresh_compiles`` is the number of
    XLA compiles no cache layer could serve — the warm-start smoke stage
    asserts it is zero in a second process sharing the cache dir."""
    with _lock:
        snap = dict(_stats)
    snap["enabled"] = _configured["dir"] is not None
    snap["cache_dir"] = _configured["dir"]
    snap["fresh_compiles"] = (
        snap["persistent_misses"] if snap["enabled"]
        else snap["backend_compiles"]
    )
    return snap


def reset_stats():
    with _lock:
        for k in _stats:
            _stats[k] = 0.0 if isinstance(_stats[k], float) else 0


# -- configuration -----------------------------------------------------------
def configure(cache_dir=None):
    """Point both cache layers at ``cache_dir`` (default: the
    ``exec_cache_dir`` flag). Idempotent; safe to call per compile. An
    empty dir disables persistence (and re-disables it if a previous test
    or run had enabled it with a since-deleted temp dir)."""
    if cache_dir is None:
        from paddle_tpu import flags

        cache_dir = flags.get("exec_cache_dir")
    cache_dir = os.path.abspath(cache_dir) if cache_dir else None
    if cache_dir == _configured["dir"]:
        if cache_dir is not None:
            _apply_max_bytes()  # a flag change must land without a dir change
        return cache_dir
    if cache_dir is None:
        jax.config.update("jax_enable_compilation_cache", False)
        _reset_jax_cache()
        _configured["dir"] = None
        return None
    # 0o700: AOT images load via pickle, so the dir is code-execution
    # trusted — keep it private to this user (see module docstring)
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    os.makedirs(os.path.join(cache_dir, "aot"), mode=0o700, exist_ok=True)
    xla_dir = os.path.join(cache_dir, "xla")
    os.makedirs(xla_dir, mode=0o700, exist_ok=True)
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", xla_dir)
    # the defaults skip "too fast / too small" entries; an executor cache
    # exists to make every process start warm, so persist everything
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # a corrupt entry must degrade to a fresh compile, never a crash
    jax.config.update("jax_raise_persistent_cache_errors", False)
    _apply_max_bytes()
    _reset_jax_cache()
    _configured["dir"] = cache_dir
    return cache_dir


def _apply_max_bytes():
    """The flag is the TOTAL budget for the cache dir: half to the XLA
    layer (jax's LRU), half to the AOT image layer (_trim_aot_dir).
    Always written — including back to -1/unbounded — so a stale cap from
    an earlier configuration can't linger."""
    max_bytes = _max_bytes()
    jax.config.update(
        "jax_compilation_cache_max_size",
        max_bytes // 2 if max_bytes > 0 else -1,
    )


def _max_bytes():
    from paddle_tpu import flags

    try:
        return int(flags.get("exec_cache_max_bytes"))
    except (KeyError, TypeError, ValueError):
        return -1


def _reset_jax_cache():
    """Drop jax's in-memory handle on the file cache so a dir change (or
    disable) takes effect mid-process; internal API, so best-effort."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def enabled():
    return _configured["dir"] is not None


# -- AOT executable images ---------------------------------------------------
def _version_tag():
    import jaxlib

    return "%s|%s" % (jax.__version__, getattr(jaxlib, "__version__", "?"))


def _args_signature(args):
    """Digest of the argument pytree structure + leaf avals: the compiled
    executable is only valid for exactly these shapes/dtypes."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = [str(treedef)]
    for leaf in leaves:
        parts.append(
            "%s%s" % (getattr(leaf, "dtype", type(leaf).__name__),
                      tuple(getattr(leaf, "shape", ())))
        )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _aot_path(disk_key, args):
    full = hashlib.sha256(
        ("%s|%s|%s" % (disk_key, _args_signature(args), _version_tag()))
        .encode()
    ).hexdigest()
    return os.path.join(_configured["dir"], "aot", full + ".exe")


def _remove_quiet(path):
    try:
        os.remove(path)
    except OSError:
        pass


def _quarantine_aot(path):
    """A corrupt AOT image is moved into ``<aot>/quarantine/``, not
    deleted: execution already degraded safely to a fresh compile, and
    quarantining both preserves the bytes for autopsy (was it a torn
    write? a bad disk? an incompatible producer?) and guarantees the
    same poisoned entry can never be re-read — deletion invites the
    writer that produced it to reproduce it. Falls back to deletion when
    the rename itself fails (e.g. a full disk)."""
    qdir = os.path.join(os.path.dirname(path), "quarantine")
    try:
        os.makedirs(qdir, mode=0o700, exist_ok=True)
        os.replace(path, os.path.join(qdir, os.path.basename(path)))
        # bounded evidence locker: a host with a flaky disk quarantines
        # on every bad read — keep the newest few, or recurring
        # corruption grows outside the FLAGS_exec_cache_max_bytes budget
        entries = sorted(
            (os.stat(p).st_mtime, p)
            for p in (os.path.join(qdir, n) for n in os.listdir(qdir))
            if os.path.isfile(p))
        for _, p in entries[:-8]:
            _remove_quiet(p)
    except OSError:
        _remove_quiet(path)
        return None
    try:
        from paddle_tpu.observability import blackbox

        if blackbox.ENABLED:
            blackbox.record("exec_cache_quarantine",
                            entry=os.path.basename(path))
    except Exception:
        pass
    return qdir


def _load_aot(path):
    if not os.path.exists(path):
        return None
    t0 = time.perf_counter()
    try:
        from paddle_tpu.resilience import chaos as _chaos

        if _chaos.ENABLED:
            _chaos.fault("aot.read")
        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        from jax.experimental import serialize_executable

        loaded = serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree
        )
    except Exception:
        # corrupt, truncated, or built by an incompatible runtime that
        # slipped past the version tag: tolerate, quarantine, recompile
        with _lock:
            _stats["aot_errors"] += 1
        _quarantine_aot(path)
        return None
    dt = time.perf_counter() - t0
    with _lock:
        _stats["aot_hits"] += 1
        _stats["compile_seconds"] += dt
        _stats["compile_seconds_warm"] += dt
    _record_compile_span("aot_image_load", dt, "warm")
    try:
        # HBM ledger (observability/memory.py): a deserialized image's
        # program+constants occupy device memory for the process's life —
        # the 'cache' kind on the live-bytes gauge. Serialized size is
        # the accountable proxy; the true on-device footprint is XLA's.
        from paddle_tpu.observability import memory as _memory

        if _memory.ENABLED:
            _memory.track("aot:" + os.path.basename(path),
                          os.path.getsize(path), "cache")
    except Exception:
        pass
    return loaded


def _store_aot(path, compiled):
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        blob = pickle.dumps(
            (payload, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL
        )
        d = os.path.dirname(path)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic: readers see old or new, never torn
        except BaseException:
            _remove_quiet(tmp)
            raise
        _trim_aot_dir(d)
    except Exception:
        with _lock:
            _stats["aot_errors"] += 1


def _trim_aot_dir(d):
    """Oldest-mtime eviction once the AOT layer exceeds its half of the
    total byte budget (the XLA layer holds the other half)."""
    budget = _max_bytes() // 2
    if budget <= 0:
        return
    try:
        entries = []
        for name in os.listdir(d):
            p = os.path.join(d, name)
            if not os.path.isfile(p):
                continue  # the quarantine subdir is not budget-evictable
            st = os.stat(p)
            entries.append((st.st_mtime, st.st_size, p))
        total = sum(e[1] for e in entries)
        for mtime, size, p in sorted(entries):
            if total <= budget:
                break
            _remove_quiet(p)
            total -= size
    except OSError:
        pass


def _guarded(loaded, jitted, path):
    """Wrap a prepared executable so failures degrade to the ordinary jit
    path instead of poisoning the run: anything on the first call (device
    topology drift, donation mismatch, a stale image) falls back
    permanently; a later TypeError (an aval change — e.g. reshaped scope
    state — that the pinned Compiled rejects but a jit retrace absorbs)
    falls back per call."""
    state = {"fn": None}

    def call(*args):
        fn = state["fn"]
        if fn is jitted:
            return jitted(*args)
        if fn is not None:
            try:
                return fn(*args)
            except TypeError:
                return jitted(*args)
        try:
            out = loaded(*args)
        except Exception:
            with _lock:
                _stats["aot_errors"] += 1
            _quarantine_aot(path)
            state["fn"] = jitted
            if any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in jax.tree_util.tree_leaves(args)
            ):
                # the failed dispatch already consumed donated buffers:
                # a retry would crash on deleted arrays — propagate the
                # real error instead of a confusing cascade
                raise
            return jitted(*args)
        state["fn"] = loaded
        return out

    return call


def prepare_executable(jitted, args, disk_key=None):
    """First-call hook for CompiledProgram/MultiStepProgram: given the
    jitted step function and the concrete call args, return the callable
    to use from now on — a deserialized AOT image on a warm start, or the
    (explicitly lowered+compiled, then serialized) fresh executable.
    Returns ``jitted`` unchanged when persistence is off, so the default
    path is byte-identical to before."""
    if configure() is None or disk_key is None:
        return jitted
    if jax.process_count() > 1:
        # multi-host executables bake in the global topology; the HLO-level
        # cache layer still applies, the AOT image layer does not
        return jitted
    path = _aot_path(disk_key, args)
    loaded = _load_aot(path)
    if loaded is not None:
        return _guarded(loaded, jitted, path)
    with _lock:
        _stats["aot_misses"] += 1
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        # an AOT-path-only failure must not take down execution; the
        # plain jit call compiles the same computation its own way
        with _lock:
            _stats["aot_errors"] += 1
        return jitted
    _store_aot(path, compiled)
    # guarded: a Compiled is pinned to these exact avals, but the same
    # CompiledProgram may later be called with reshaped scope state —
    # the plain jit path retraces for that case, so fall back to it
    return _guarded(compiled, jitted, path)
