"""Device places and variable types.

Reference parity: ``paddle/fluid/platform/place.h:25,36,51`` (Place variant)
and ``paddle/fluid/framework/framework.proto:105`` (VarType). On TPU the
device runtime is owned by JAX/PJRT, so a Place resolves to a ``jax.Device``
instead of carrying CUDA stream state.
"""

import numpy as np


class Place(object):
    """Base device tag. Resolves lazily to a jax.Device."""

    _kind = None  # platform preference, e.g. "tpu" / "cpu"

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def jax_device(self):
        import jax

        if self._kind is not None:
            # Ask the backend for this platform directly: jax.devices()
            # only lists the DEFAULT platform, so with an accelerator
            # plugin loaded a CPUPlace would otherwise silently resolve to
            # the accelerator.
            try:
                devs = jax.devices(self._kind)
                # Under jax.distributed, jax.devices() is the GLOBAL list;
                # an Executor place must be a device this process owns.
                local = [
                    d for d in devs if d.process_index == jax.process_index()
                ]
                devs = local or devs
                return devs[self.device_id % len(devs)]
            except RuntimeError:
                pass  # platform not present; fall through to default
        devices = jax.devices()
        return devices[self.device_id % len(devices)]

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)


class TPUPlace(Place):
    """The TPU device tag — the ``CUDAPlace`` analog (place.h:36). Falls back
    to the default JAX backend when no TPU platform is present (e.g. unit
    tests on the virtual CPU mesh)."""

    _kind = "tpu"

    def jax_device(self):
        import jax

        devices = jax.local_devices()
        non_cpu = [d for d in devices if d.platform.lower() != "cpu"]
        pool = non_cpu if non_cpu else devices
        return pool[self.device_id % len(pool)]


class CPUPlace(Place):
    _kind = "cpu"


class CUDAPlace(TPUPlace):
    """Porting-compat alias (place.h:36): there is no CUDA in this
    framework — a script's ``fluid.CUDAPlace(0)`` maps to the accelerator
    place (TPUPlace), with a one-time warning so the difference is
    visible."""

    _warned = False

    def __init__(self, device_id=0):
        super(CUDAPlace, self).__init__(device_id)
        if not CUDAPlace._warned:
            CUDAPlace._warned = True
            import warnings

            warnings.warn(
                "CUDAPlace maps to the TPU/accelerator place in "
                "paddle_tpu (no CUDA backend exists)", UserWarning,
                stacklevel=2)


class CUDAPinnedPlace(CPUPlace):
    """Porting-compat alias (place.h:51): pinned host memory is a CUDA
    transfer-staging concept; host arrays feed the accelerator directly
    here, so this is the CPU place."""

    def __init__(self, device_id=0):
        super(CUDAPinnedPlace, self).__init__(device_id)


class VarType(object):
    """Variable type tags (framework.proto:105 VarType.Type)."""

    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"
    STEP_SCOPES = "step_scopes"
    LOD_RANK_TABLE = "lod_rank_table"
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    READER = "reader"
    RAW = "raw"
    # scalar data types live on Variable.dtype as canonical numpy names


_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}

_SUPPORTED = (
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "int8",
    "uint8",
    "int16",
    "int32",
    "int64",
    "bool",
)


def canonical_dtype(dtype):
    """Normalize any dtype spec (str/np.dtype/jnp dtype) to a canonical name."""
    if dtype is None:
        return "float32"
    if hasattr(dtype, "name"):
        name = dtype.name
    else:
        name = str(dtype)
    name = _DTYPE_ALIASES.get(name, name)
    if name not in _SUPPORTED:
        raise ValueError("unsupported dtype %r" % (dtype,))
    return name


def np_dtype(dtype):
    name = canonical_dtype(dtype)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def is_float_dtype(dtype):
    return canonical_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def core_version():
    return "paddle_tpu-core-0.1"


def device_dtype(dtype):
    """The dtype a value of `dtype` actually takes ON DEVICE: with jax
    x64 disabled (the TPU default), int64/uint64/float64 narrow to their
    32-bit forms. Lowerings request this directly instead of asking jnp
    for a width it will warn about and truncate anyway; host-side code
    (feeds, .npy persistence) keeps the declared width via np_dtype."""
    import jax.dtypes

    # the supported API for "what does this dtype canonicalize to on
    # device": narrows 64-bit widths iff x64 is off, tracking the flag
    # across jax versions
    return str(jax.dtypes.canonicalize_dtype(np_dtype(dtype)))
