"""Hierarchical Scope: name -> runtime value symbol table.

Reference parity: ``paddle/fluid/framework/scope.h:41`` and
``variable.h:26``. A Variable here is a thin type-erased holder whose value
is a ``jax.Array`` (device tensor), a host ``LoDTensor``, or any Python
object (rank tables, reader state...). Child scopes serve RNN iterations and
per-device local scopes in the ParallelExecutor.
"""


class ScopeVariable(object):
    __slots__ = ("name", "value", "lod")

    def __init__(self, name):
        self.name = name
        self.value = None
        self.lod = None  # optional LoD metadata attached to a device array

    def get_tensor(self):
        return self.value

    def set(self, value, lod=None):
        self.value = value
        if lod is not None:
            self.lod = lod


class Scope(object):
    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent
        self._kids = []

    # -- scope.h API surface ------------------------------------------------
    def var(self, name):
        """Find-or-create in this scope (Scope::Var)."""
        v = self._vars.get(name)
        if v is None:
            v = ScopeVariable(name)
            self._vars[name] = v
        return v

    def find_var(self, name):
        """Search this scope then ancestors (Scope::FindVar)."""
        scope = self
        while scope is not None:
            v = scope._vars.get(name)
            if v is not None:
                return v
            scope = scope._parent
        return None

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)

    def new_scope(self):
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars)

    # -- convenience --------------------------------------------------------
    def set_value(self, name, value, lod=None):
        self.var(name).set(value, lod=lod)

    def get_value(self, name):
        v = self.find_var(name)
        return None if v is None else v.value

    def has(self, name):
        return self.find_var(name) is not None
