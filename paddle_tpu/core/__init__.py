"""Engine core: the role played by ``paddle/fluid/pybind`` + C++ framework in
the reference (paddle/fluid/framework/), rebuilt on JAX/XLA.

Submodules:
  - types: Place / VarType / dtype mapping
  - scope: hierarchical name->Variable symbol table (scope.h:41 parity)
  - op_registry: operator schema + JAX lowering registry (op_registry.h parity)
  - lowering: block -> JAX function tracer (the Executor's compiler)
  - lod: host-side LoDTensor (lod_tensor.h:110 parity)
"""
