"""SelectedRows: sparse row-subset tensor {rows, value, height}.

Reference parity: ``paddle/fluid/framework/selected_rows.h:32`` and the
selected_rows_functor math — the reference's representation for embedding
gradients and sparse pserver updates. On TPU the compiled path keeps
gradients dense (XLA scatter-add onto the row-sharded table rides the mesh
collectives), so this host-side type serves the *interchange* role: sparse
checkpoint shards, host-offloaded embedding updates, and feed/fetch of
sparse values.
"""

import numpy as np


class SelectedRows(object):
    def __init__(self, rows, value, height):
        self.rows = np.asarray(rows, np.int64).reshape(-1)
        self.value = np.asarray(value)
        if self.value.shape[0] != self.rows.shape[0]:
            raise ValueError(
                "value has %d rows, rows index has %d"
                % (self.value.shape[0], self.rows.shape[0])
            )
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def to_dense(self):
        """Scatter-ADD duplicate rows into a dense [height, ...] array
        (selected_rows_functor.cc merge-add semantics)."""
        dense = np.zeros(self.shape, self.value.dtype)
        np.add.at(dense, self.rows, self.value)
        return dense

    @classmethod
    def from_dense_rows(cls, dense, rows):
        """Pick the given rows out of a dense table."""
        dense = np.asarray(dense)
        rows = np.asarray(rows, np.int64).reshape(-1)
        return cls(rows, dense[rows], dense.shape[0])

    def merge_rows(self):
        """Coalesce duplicate row ids (merge_add): unique rows, summed
        values — what the pserver applies for sparse grads."""
        uniq, inv = np.unique(self.rows, return_inverse=True)
        merged = np.zeros((len(uniq),) + self.value.shape[1:],
                          self.value.dtype)
        np.add.at(merged, inv, self.value)
        return SelectedRows(uniq, merged, self.height)

    def apply_sgd(self, table, lr):
        """In-place sparse SGD row update on a dense host table (the
        pserver optimize-block capability for is_sparse grads)."""
        m = self.merge_rows()
        table[m.rows] -= lr * m.value
        return table

    def __repr__(self):
        return "SelectedRows(height=%d, nnz_rows=%d, row_dim=%s)" % (
            self.height, len(self.rows), self.value.shape[1:]
        )
