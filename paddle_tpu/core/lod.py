"""Host-side LoDTensor: ndarray + Level-of-Detail ragged-sequence index.

Reference parity: ``paddle/fluid/framework/lod_tensor.h:110``. LoD is a list
of offset vectors describing nested variable-length sequences, e.g.
``[[0, 2, 5]]`` = two sequences of lengths 2 and 3 packed along axis 0.

TPU-first stance: XLA needs static shapes, so the *device* representation
of ragged data is dense padded + lengths/segment-ids (see layers/sequence
lowerings); LoDTensor remains the host API so Fluid-style feeding of
variable-length data keeps working, with conversion at the feed boundary.
"""

import numpy as np


class LoDTensor(object):
    def __init__(self, array=None, lod=None):
        self._array = None if array is None else np.asarray(array)
        self._lod = [list(level) for level in (lod or [])]

    # -- reference API surface (pybind.cc Tensor/LoDTensor bindings) --------
    def set(self, array, place=None):
        self._array = np.asarray(array)

    def set_lod(self, lod):
        self._lod = [list(level) for level in lod]

    def lod(self):
        return [list(level) for level in self._lod]

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        prev_len = None
        for level in self._lod:
            if len(level) < 2 or level[0] != 0:
                return False
            if any(b > a for a, b in zip(level[1:], level[:-1])):
                return False
            if prev_len is not None and level[-1] != prev_len:
                pass  # nested levels index into the next level's entries
            prev_len = len(level) - 1
        return self._lod[-1][-1] == (0 if self._array is None else self._array.shape[0])

    def recursive_sequence_lengths(self):
        return [
            [b - a for a, b in zip(level[:-1], level[1:])] for level in self._lod
        ]

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = [list(np.cumsum([0] + list(level))) for level in lengths]

    def shape(self):
        return () if self._array is None else tuple(self._array.shape)

    def numpy(self):
        return self._array

    def __array__(self, dtype=None):
        a = self._array
        return a if dtype is None else a.astype(dtype)

    # -- ragged <-> dense conversion (device boundary) ----------------------
    def to_padded(self, pad_value=0.0, max_len=None):
        """Innermost-level split -> (padded [num_seq, max_len, ...], lengths)."""
        if not self._lod:
            raise ValueError("tensor has no LoD")
        offsets = self._lod[-1]
        lengths = np.array(
            [b - a for a, b in zip(offsets[:-1], offsets[1:])], dtype=np.int32
        )
        ml = int(max_len or (lengths.max() if len(lengths) else 0))
        trailing = self._array.shape[1:]
        out = np.full((len(lengths), ml) + trailing, pad_value, self._array.dtype)
        for i, (a, b) in enumerate(zip(offsets[:-1], offsets[1:])):
            n = min(b - a, ml)
            out[i, :n] = self._array[a : a + n]
        return out, lengths

    @staticmethod
    def from_padded(padded, lengths):
        padded = np.asarray(padded)
        lengths = np.asarray(lengths).astype(np.int64)
        pieces = [padded[i, : int(n)] for i, n in enumerate(lengths)]
        flat = (
            np.concatenate(pieces, axis=0)
            if pieces
            else np.zeros((0,) + padded.shape[2:], padded.dtype)
        )
        return LoDTensor(flat, [list(np.cumsum([0] + list(lengths)))])

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape(), self._lod)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """fluid.create_lod_tensor parity (python/paddle/fluid/lod_tensor.py)."""
    if isinstance(data, LoDTensor):
        t = LoDTensor(data.numpy())
    else:
        t = LoDTensor(np.asarray(data))
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """fluid.create_random_int_lodtensor parity
    (python/paddle/fluid/lod_tensor.py:92): random ints shaped
    [sum(innermost lens)] + base_shape with the given nesting."""
    flat = recursive_seq_lens[-1]
    total = int(np.sum(flat))
    data = np.random.randint(
        low, high + 1, [total] + list(base_shape)).astype('int64')
    return create_lod_tensor(data, recursive_seq_lens, place)
