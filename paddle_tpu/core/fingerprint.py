"""Structural program fingerprints: content-addressed executable cache keys.

The Executor used to key its executable cache on ``id(program)`` /
``id(scope)``. CPython reuses ``id()`` after GC, so a dead program's key
could alias a freshly-built program and serve a stale executable — and two
structurally identical programs (Predictor.Clone() threads, a re-built
bench program, a second Executor instance) could never share a compile.

``program_fingerprint`` walks every block in order and hashes the canonical
content that determines what the block lowers TO: op types, input/output
slot wiring, attrs, and the var symbol table specs (shape/dtype/lod/
persistable/stop_gradient) the lowerings and the feed-cast policy consult.
Runtime-only knobs (``random_seed`` feeds the step PRNG key, which is a
function *argument*) stay out. The digest is memoized per ``_version`` so
steady-state runs hash nothing; any graph surgery bumps ``_version``
(framework.py ``_bump_version``) and invalidates the memo.

``trace_flags_key`` joins it in every cache key: these flags are read at
trace time inside op lowerings, so toggling one must recompile rather than
reuse a stale executable.
"""

import hashlib

# Flags whose value changes what the block lowers TO (not just runtime
# behavior); they join the executable cache key so toggling recompiles.
# flash_backward is read inside the flash-attention custom_vjp at trace
# time; build-time flags (fused_ce) already show up in the program
# structure and need no entry here.
TRACE_FLAGS = ("use_pallas_lstm", "use_pallas_gru", "remat_gradients",
               "conv_nhwc", "attention_impl", "flash_backward")


def trace_flags_key():
    from paddle_tpu import flags

    return tuple((n, flags.get(n)) for n in TRACE_FLAGS)


def _encode(value, update):
    """Feed ``value`` into the hash as an unambiguous, type-tagged byte
    stream (so e.g. 1 vs True vs "1" vs 1.0 hash differently and list
    nesting cannot be confused with concatenation)."""
    if value is None:
        update(b"N")
    elif value is True:
        update(b"T")
    elif value is False:
        update(b"F")
    elif isinstance(value, int):
        update(b"i%d;" % value)
    elif isinstance(value, float):
        update(b"f")
        update(repr(value).encode())
        update(b";")
    elif isinstance(value, str):
        b = value.encode("utf-8", "surrogatepass")
        update(b"s%d:" % len(b))
        update(b)
    elif isinstance(value, bytes):
        update(b"b%d:" % len(value))
        update(value)
    elif isinstance(value, (list, tuple)):
        update(b"[")
        for item in value:
            _encode(item, update)
        update(b"]")
    elif isinstance(value, dict):
        update(b"{")
        for k in sorted(value, key=repr):
            _encode(k, update)
            update(b"=")
            _encode(value[k], update)
        update(b"}")
    elif isinstance(value, (set, frozenset)):
        update(b"<")
        for item in sorted(value, key=repr):
            _encode(item, update)
        update(b">")
    else:
        try:
            import numpy as np

            if isinstance(value, np.ndarray):
                update(b"a")
                _encode((str(value.dtype), value.shape), update)
                update(np.ascontiguousarray(value).tobytes())
                return
            if isinstance(value, np.generic):
                _encode(value.item(), update)
                return
        except ImportError:  # pragma: no cover
            pass
        # Last resort (enum-ish objects, Places...): repr is stable within
        # a process and across processes for value-like types.
        update(b"r")
        update(repr(value).encode("utf-8", "replace"))
        update(b";")


def _encode_var(name, v, update):
    _encode(
        (
            name,
            None if v.shape is None else tuple(v.shape),
            v.dtype,
            getattr(v, "lod_level", 0),
            bool(v.persistable),
            bool(getattr(v, "stop_gradient", False)),
            getattr(v, "type", None),
            bool(getattr(v, "is_data", False)),
        ),
        update,
    )


def _encode_op(op, update):
    _encode(op.type, update)
    _encode(
        sorted((slot, tuple(names)) for slot, names in op.inputs.items()),
        update,
    )
    _encode(
        sorted((slot, tuple(names)) for slot, names in op.outputs.items()),
        update,
    )
    _encode(op.attrs, update)


def program_fingerprint(program):
    """Canonical content hash (hex sha256) of a Program's structure.

    Memoized on ``program._version``: mutation through the framework API
    bumps the version and forces a re-hash; direct attribute pokes that
    bypass ``_bump_version`` are invisible here exactly as they were
    invisible to the reference's version-keyed program cache.
    """
    memo = getattr(program, "_fingerprint_memo", None)
    if memo is not None and memo[0] == program._version:
        return memo[1]
    h = hashlib.sha256()
    update = h.update
    _encode(
        (program._is_test, getattr(program, "_amp_dtype", None)), update
    )
    for block in program.blocks:
        _encode((block.idx, block.parent_idx), update)
        for name in sorted(block.vars):
            _encode_var(name, block.vars[name], update)
        for op in block.ops:
            _encode_op(op, update)
    digest = h.hexdigest()
    program._fingerprint_memo = (program._version, digest)
    return digest


def executable_key(program, feed_specs, fetch_names, scope_names, extra=()):
    """Stable cross-process digest for one executable: the structural
    fingerprint x feed specs x fetch set x scope signature x trace flags
    x caller extras (device platform/kind, steps, mesh...). The
    persistent exec cache (core/exec_cache.py) appends jax/jaxlib
    versions before this touches disk."""
    h = hashlib.sha256()
    update = h.update
    update(program_fingerprint(program).encode())
    _encode(
        tuple(sorted(
            (n, tuple(s), str(d)) for n, (s, d) in feed_specs.items()
        )),
        update,
    )
    _encode(tuple(fetch_names), update)
    _encode(tuple(sorted(scope_names)), update)
    _encode(trace_flags_key(), update)
    _encode(tuple(extra), update)
    return h.hexdigest()
