"""Block -> JAX function compiler: the execution engine's core.

This replaces the reference's per-op interpreter loop
(``paddle/fluid/framework/executor.cc:392-404`` RunPreparedContext) with a
whole-program trace: every op's registered lowering rule is applied in
program order to a symbolic environment, producing ONE JAX function for the
whole block, which ``jax.jit`` compiles to a single fused XLA executable.
SSA-graph scheduling (``details/threaded_ssa_graph_executor.cc``) becomes
XLA's job; gradient ops re-trace forward rules under jax.vjp and XLA CSE
dedups the recompute.
"""

import threading

import jax
import jax.numpy as jnp

from paddle_tpu.observability import lock_witness
from paddle_tpu.core import op_registry
from paddle_tpu.core.op_registry import LowerContext, normalize_outputs

# Ops the engine interprets itself rather than via registry lowerings.
_STRUCTURAL_OPS = ("feed", "fetch")


def _valid(names):
    return [n for n in names if n]


class BlockLowerer(object):
    """Traces the ops of one block over a name->value environment."""

    def __init__(self, program, block_idx=0, is_test=False):
        self.program = program
        self.block = program.block(block_idx)
        self.is_test = is_test
        self._reshard_names = None  # lazy: vars carrying a reshard_spec

    def analyze(self, scope_names, feed_names):
        """Classify variable usage for the compiled step signature.

        Returns (state_in, state_out):
          state_in: persistable vars the block reads that must come from the
            scope (function inputs);
          state_out: persistable vars the block actually WRITES (function
            outputs, written back to the scope). Read-only state (inference
            params) stays out of state_out so CompiledProgram never donates
            its buffers — donation would invalidate scope arrays shared
            with concurrent runs.
        """
        defined = set(feed_names)
        state_in = []
        state_out = []
        seen_in = set()
        seen_out = set()
        for op, block in self._iter_ops_recursive(self.block):
            for name in _valid(op.input_arg_names()):
                if name in defined or name in seen_in:
                    continue
                v = block._find_var_recursive(name)
                if v is not None and v.persistable:
                    if name in scope_names:
                        seen_in.add(name)
                        state_in.append(name)
                    # else: must be produced earlier in the block or it is a
                    # genuine "not initialized" error surfaced at trace time.
            for name in _valid(op.output_arg_names()):
                defined.add(name)
                v = block._find_var_recursive(name)
                if v is not None and v.persistable and name not in seen_out:
                    seen_out.add(name)
                    state_out.append(name)
        return state_in, state_out

    def _iter_ops_recursive(self, block):
        for op in block.ops:
            yield op, block
            for attr in ("sub_block", "block", "true_block", "false_block"):
                idx = op.attrs.get(attr)
                if isinstance(idx, int) and 0 <= idx < self.program.num_blocks:
                    sub = self.program.block(idx)
                    for item in self._iter_ops_recursive(sub):
                        yield item

    def lower_into(self, env, step_key):
        """Run every op's lowering against env (name -> traced value)."""
        for op in self.block.ops:
            self.lower_op(op, env, step_key)
        return env

    def lower_op(self, op, env, step_key):
        if op.type in _STRUCTURAL_OPS:
            return
        opdef = op_registry.get_op_def(op.type)
        ins = {}
        for slot in opdef.input_slots():
            names = op.input(slot)
            if names:
                try:
                    if slot.endswith("@GRAD"):
                        # Grad slots keep positional alignment with their
                        # forward outputs: a hole (no incoming grad for that
                        # output) is None, not dropped.
                        ins[slot] = [env[n] if n else None for n in names]
                    else:
                        ins[slot] = [env[n] for n in _valid(names)]
                except KeyError as e:
                    raise RuntimeError(
                        "op %s reads uninitialized variable %s "
                        "(not fed, not persistable-in-scope, not produced "
                        "earlier in the block)" % (op.type, e)
                    )
        amp = getattr(self.program, "_amp_dtype", None)
        if amp:
            from paddle_tpu.core.amp import apply_amp_casts

            ins = apply_amp_casts(op.type, ins, amp)
        ctx = LowerContext(
            op,
            rng=_make_rng(step_key, op.attrs),
            is_test=self.is_test or op.attrs.get("is_test", False),
            block_lowerer=self,
        )
        outs = normalize_outputs(opdef, opdef.lower(ctx, ins, op.attrs))
        for slot, arrs in outs.items():
            names = op.output(slot)
            for name, val in zip(names, arrs):
                if name and val is not None:
                    env[name] = self._apply_reshard(name, val)

    def _apply_reshard(self, name, val):
        """Explicit resharding point: a var the sharding transpiler
        (parallel/sharding.py) marked with ``reshard_spec`` — a
        tp-partial activation flowing into an op with no tp story — gets
        a ``with_sharding_constraint`` at its producer, so the conflict
        resolves as ONE visible collective instead of silent replication
        of the producing weight. Applies only under a mesh compile whose
        axes cover the spec (a later single-device or legacy-mesh compile
        of the same annotated program is untouched)."""
        names = self._reshard_names
        if names is None:
            # one sweep over the block chain; the common (unannotated)
            # case then skips the per-output recursive var lookup
            names = set()
            b = self.block
            while b is not None:
                for n, bv in b.vars.items():
                    if getattr(bv, "reshard_spec", None) is not None:
                        names.add(n)
                b = b.parent_block
            self._reshard_names = names
        if name not in names:
            return val
        v = self.block._find_var_recursive(name)
        spec = getattr(v, "reshard_spec", None)
        if spec is None:
            return val
        mesh = ambient_mesh()
        if mesh is None:
            return val
        axes = set()
        for entry in spec:
            if isinstance(entry, str):
                axes.add(entry)
            elif entry is not None:
                axes.update(entry)
        if not axes.issubset(set(mesh.shape)):
            return val
        from jax.sharding import NamedSharding, PartitionSpec

        try:
            return jax.lax.with_sharding_constraint(
                val, NamedSharding(mesh, PartitionSpec(*spec)))
        except Exception:
            # rank drift between annotation and trace (reshaped program):
            # the constraint is an optimization hint, never a hard failure
            return val

    def lower_sub_block(self, block_idx, env, step_key):
        """Lower a nested block (control-flow mega-ops) in-place on env."""
        sub = BlockLowerer(self.program, block_idx, is_test=self.is_test)
        for op in sub.block.ops:
            sub.lower_op(op, env, step_key)
        return env


def _make_rng(step_key, attrs):
    rng_id = attrs.get("__rng_id__", 0)
    seed = attrs.get("seed", 0)

    def rng():
        if seed:
            # Fixed-seed ops (fix_seed semantics): same stream every step.
            return jax.random.fold_in(jax.random.PRNGKey(seed), rng_id)
        return jax.random.fold_in(step_key, rng_id)

    return rng


_AMBIENT_MESH = []  # trace-time stack: the mesh a sharded compile runs under
_AMBIENT_PLATFORM = []  # trace-time stack: platform the compile targets


def ambient_mesh():
    """The jax.sharding.Mesh of the ParallelExecutor compile currently
    being traced, or None. Lets op lowerings opt into mesh-aware forms
    (e.g. scaled_dot_product_attention's seq_parallel_axis routing to
    ring attention) without plumbing the mesh through every rule."""
    return _AMBIENT_MESH[-1] if _AMBIENT_MESH else None


def ambient_platform():
    """The platform ('cpu', 'tpu', ...) of the device the compile being
    traced is pinned to, or None when unpinned. Pallas kernel entry
    points use this to pick interpret mode: with several backends loaded
    (the tunnel TPU plugin + CPU), ``jax.default_backend()`` names the
    highest-priority platform, NOT the Place this executable targets."""
    return _AMBIENT_PLATFORM[-1] if _AMBIENT_PLATFORM else None


def target_platform():
    """Platform the enclosing compile targets: the executor's pinned
    Place when lowering a program, else the process default backend."""
    plat = ambient_platform()
    if plat is not None:
        return plat
    return jax.default_backend()


def is_tpu_target():
    """True when the enclosing compile targets a non-CPU backend —
    the signal Pallas kernel entry points key interpret mode on."""
    return target_platform() not in ("cpu",)


def build_step_fn(program, feed_names, fetch_names, state_in, state_out,
                  is_test=False, mesh=None, platform=None):
    """Build the pure step function: (state, feeds, key) -> (new_state, fetches)."""
    lowerer = BlockLowerer(program, 0, is_test=is_test)

    def step(state, feeds, key):
        env = {}
        env.update(state)
        env.update(feeds)
        _AMBIENT_MESH.append(mesh)
        _AMBIENT_PLATFORM.append(platform)
        try:
            lowerer.lower_into(env, key)
        finally:
            _AMBIENT_MESH.pop()
            _AMBIENT_PLATFORM.pop()
        new_state = {}
        for n in state_out:
            if n in env:
                new_state[n] = env[n]
        fetches = []
        for n in fetch_names:
            if n not in env:
                raise RuntimeError(
                    "fetch variable %r was not produced by the program" % n
                )
            fetches.append(env[n])
        return new_state, fetches

    return step


class _LazyExecutable(object):
    """First-call executable resolution through the persistent cache
    (core/exec_cache.py): an AOT image on a warm start, a fresh (then
    serialized) compile otherwise. The executor stamps _exec_cache_key
    after construction; None keeps the plain jit path. Locked: the
    process-global registry shares one instance across serving threads,
    and two concurrent first calls must not both pay the compile."""

    def _init_lazy_exec(self):
        self._exec = None
        self._exec_cache_key = None
        self._exec_lock = lock_witness.make_lock("core.lowering.exec")

    def _resolve_exec(self, args):
        fn = self._exec
        if fn is None:
            with self._exec_lock:
                fn = self._exec
                if fn is None:
                    import time as _time

                    from paddle_tpu import profiler
                    from paddle_tpu.core import exec_cache
                    from paddle_tpu.observability import watchdog

                    t0 = _time.perf_counter()
                    # a fresh compile can legitimately run minutes while
                    # the watchdog's step-derived timeout is seconds —
                    # slow-but-alive host work must not read as a hang
                    with watchdog.suspend():
                        fn = exec_cache.prepare_executable(
                            self.jitted, args, self._exec_cache_key
                        )
                    # first-call resolution (AOT deserialize or lower+
                    # compile+serialize) in the unified trace; the inner
                    # backend compile appears as its own span via the
                    # jax.monitoring taps
                    profiler.record_span(
                        "executable_resolve", t0, _time.perf_counter(),
                        cat="compile")
                    self._exec = fn
        return fn


class CompiledProgram(_LazyExecutable):
    """One jitted executable for a (program-version, shapes, fetches) key.

    With ``shardings`` (a ShardingPolicy from paddle_tpu.parallel), the jit
    runs under GSPMD over the policy's mesh: state/feed in_shardings are
    taken from the policy and XLA inserts the collectives — the
    ParallelExecutor/MultiDevSSAGraphBuilder capability without building
    per-device SSA graphs.
    """

    def __init__(
        self,
        program,
        feed_specs,
        fetch_names,
        scope_names,
        is_test=False,
        shardings=None,
        device=None,
    ):
        self.fetch_names = list(fetch_names)
        lowerer = BlockLowerer(program, 0, is_test=is_test)
        self.state_in, self.state_out = lowerer.analyze(
            scope_names, set(feed_specs)
        )
        self.step = build_step_fn(
            program,
            list(feed_specs),
            self.fetch_names,
            self.state_in,
            self.state_out,
            is_test=is_test,
            mesh=shardings.mesh if shardings is not None else None,
            platform=getattr(device, "platform", None),
        )
        # Donate ONLY state the program replaces (optimizer updates, BN
        # stats). Donating untouched state (e.g. params in an inference
        # program) would invalidate the scope's live buffers on backends
        # with real donation — a use-after-free for any later run or a
        # concurrent clone sharing the scope.
        self.mutable_state = sorted(set(self.state_in) & set(self.state_out))
        self.frozen_state = sorted(set(self.state_in) - set(self.state_out))
        step = self.step

        def split_step(mut_state, frozen_state, feeds, key):
            state = dict(frozen_state)
            state.update(mut_state)
            return step(state, feeds, key)

        self.shardings = shardings
        self._init_lazy_exec()
        if shardings is None:
            if device is not None:
                # Pin the executable to the Place's device: with multiple
                # backends loaded (e.g. the TPU plugin + CPU), jit would
                # otherwise follow the default platform, not the Place.
                s = jax.sharding.SingleDeviceSharding(device)
                self.jitted = jax.jit(
                    split_step, donate_argnums=(0,), in_shardings=s,
                    out_shardings=s,
                )
            else:
                self.jitted = jax.jit(split_step, donate_argnums=(0,))
        else:
            mut_s = {n: shardings.state_sharding(n)
                     for n in self.mutable_state}
            frz_s = {n: shardings.state_sharding(n)
                     for n in self.frozen_state}
            feed_s = {
                n: shardings.feed_sharding(n, shape=feed_specs[n][0])
                for n in feed_specs
            }
            state_out_s = {n: shardings.state_sharding(n) for n in self.state_out}
            self.jitted = jax.jit(
                split_step,
                in_shardings=(mut_s, frz_s, feed_s, shardings.replicated()),
                out_shardings=(state_out_s, None),
                donate_argnums=(0,),
            )

    def __call__(self, state, feeds, key):
        mut = {n: state[n] for n in self.mutable_state}
        frz = {n: state[n] for n in self.frozen_state}
        fn = self._resolve_exec((mut, frz, feeds, key))
        return fn(mut, frz, feeds, key)


class MultiStepProgram(_LazyExecutable):
    """K training steps compiled into ONE XLA executable via lax.scan.

    SURVEY §7 hard part (c): per-step Python dispatch costs a host round
    trip per step (severe through a tunnel, nonzero everywhere). Scanning
    the step function amortizes dispatch to one call per K steps; state
    chains on device through the scan carry, and per-step fetches come
    back stacked [K, ...] (the loss curve, not just the last value).

    Feeds are constant across the K steps (synthetic-input benches) — real
    input pipelines should use the in-graph reader ops instead, which need
    no feeds at all. Requires state_out ⊆ state_in (training programs
    satisfy this: optimizer/BN state is read-modify-write).
    """

    def __init__(self, program, steps, feed_specs, fetch_names, scope_names,
                 is_test=False, device=None, stack_fetches=False):
        self.steps = int(steps)
        if self.steps <= 0:
            raise ValueError("multi-step needs steps >= 1, got %d" % steps)
        self.fetch_names = list(fetch_names)
        lowerer = BlockLowerer(program, 0, is_test=is_test)
        self.state_in, self.state_out = lowerer.analyze(
            scope_names, set(feed_specs)
        )
        extra_out = set(self.state_out) - set(self.state_in)
        if extra_out:
            raise RuntimeError(
                "multi-step compilation needs state_out ⊆ state_in; program "
                "creates persistables mid-run: %s" % sorted(extra_out)
            )
        step = build_step_fn(
            program, list(feed_specs), self.fetch_names,
            self.state_in, self.state_out, is_test=is_test,
            platform=getattr(device, "platform", None),
        )
        self.mutable_state = sorted(
            set(self.state_in) & set(self.state_out))
        self.frozen_state = sorted(
            set(self.state_in) - set(self.state_out))
        n_steps = self.steps

        def multi(mut_state, frozen_state, feeds, key):
            import jax.numpy as jnp

            def body(carry, i):
                state = dict(frozen_state)
                state.update(carry)
                new_state, fetches = step(
                    state, feeds, jax.random.fold_in(key, i)
                )
                carry = {n: new_state[n] for n in carry}
                return carry, tuple(fetches)

            if stack_fetches:
                # per-step fetch trajectory [K, ...] — costs scan-output
                # buffers every iteration; use for small diagnostics only
                carry, ys = jax.lax.scan(
                    body, mut_state, jnp.arange(n_steps)
                )
                return carry, list(ys)

            # default: fetches from the LAST step ride the carry — no
            # per-iteration output buffers in the scan
            def body_carry(carry, i):
                st, _ = carry
                st2, fetches = body(st, i)
                return (st2, tuple(fetches)), None

            _, fetch0 = jax.eval_shape(
                lambda c: body(c, jnp.asarray(0)), mut_state
            )
            init_f = tuple(
                jnp.zeros(f.shape, f.dtype) for f in fetch0
            )
            (carry, fetches), _ = jax.lax.scan(
                body_carry, (mut_state, init_f), jnp.arange(n_steps)
            )
            return carry, list(fetches)

        if device is not None:
            s = jax.sharding.SingleDeviceSharding(device)
            self.jitted = jax.jit(
                multi, donate_argnums=(0,), in_shardings=s, out_shardings=s
            )
        else:
            self.jitted = jax.jit(multi, donate_argnums=(0,))
        self._init_lazy_exec()

    def __call__(self, state, feeds, key):
        mut = {n: state[n] for n in self.mutable_state}
        frz = {n: state[n] for n in self.frozen_state}
        fn = self._resolve_exec((mut, frz, feeds, key))
        return fn(mut, frz, feeds, key)
