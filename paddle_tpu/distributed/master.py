"""Elastic data-dispatch master: leased tasks, timeout requeue, failure
discard, pass management, crash-recoverable snapshots.

Reference parity: go/master/service.go — ``SetDataset``/``partition``
(:106), ``GetTask`` (:368, lease + timeout timer), ``TaskFinished`` (:411),
``TaskFailed`` (:455, requeue until failure_max then discard), snapshot-to-
store recovery (:166,207) — and go/master/client.go's task-backed reader.

TPU-first differences: the store is a local file (or any object with
save/load) instead of etcd — on Cloud TPU pods the coordinator's disk or
GCS plays that role; the wire protocol is newline-delimited JSON over TCP
instead of Go net/rpc, so Python workers need no extra deps. The trainer
process is stateless: any worker can fetch any task, so killing a worker
mid-epoch only delays its leased tasks until the lease times out and the
task is re-dispatched (the elastic-training contract the reference's
fault-tolerance docs describe).
"""

import json
import os
import socket
import socketserver
import threading
import time

__all__ = ["Task", "MasterService", "MasterClient", "task_reader"]


class Task(object):
    __slots__ = ("task_id", "chunks", "epoch", "num_failures")

    def __init__(self, task_id, chunks, epoch=0, num_failures=0):
        self.task_id = task_id
        self.chunks = list(chunks)
        self.epoch = epoch
        self.num_failures = num_failures

    def to_json(self):
        return {
            "task_id": self.task_id,
            "chunks": self.chunks,
            "epoch": self.epoch,
            "num_failures": self.num_failures,
        }

    @staticmethod
    def from_json(d):
        return Task(d["task_id"], d["chunks"], d["epoch"], d["num_failures"])


class _Errors(object):
    PASS_BEFORE = "pass_before"
    PASS_AFTER = "pass_after"
    NO_MORE_AVAILABLE = "no_more_available"
    ALL_FAILED = "all_task_failed"


class MasterService(object):
    """In-process task-queue service; optionally served over TCP."""

    def __init__(self, chunks_per_task=1, timeout_s=5.0, failure_max=3,
                 snapshot_path=None, snapshot_interval_s=0.5):
        """snapshot_interval_s: write-throttle window for per-lease
        snapshot churn (see _snapshot); structural transitions always
        force a write. Crash-recovery tests raise it to pin exactly
        which state a simulated kill -9 loses."""
        self._chunks_per_task = max(1, int(chunks_per_task))
        self._timeout_s = timeout_s
        self._failure_max = failure_max
        self._snapshot_path = snapshot_path
        self._mu = threading.RLock()
        self._todo = []  # [Task]
        self._pending = {}  # task_id -> (Task, lease_deadline)
        self._done = []
        self._failed = []
        self._cur_pass = 0
        self._all_chunks = []
        self._server = None
        self._watcher = None
        self._closed = threading.Event()
        self._snapshot_interval_s = float(snapshot_interval_s)
        self._last_snapshot = 0.0
        self._snapshot_dirty = False
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset / partition (service.go:106,280) ---------------------------

    def set_dataset(self, chunks):
        """chunks: list of opaque JSON-serializable chunk descriptors (file
        paths, (file, offset) pairs...). Partitioned chunks_per_task each."""
        with self._mu:
            self._all_chunks = list(chunks)
            if not self._todo and not self._pending and not self._done:
                self._todo = self._partition(self._all_chunks)
                self._snapshot(force=True)

    def _partition(self, chunks):
        tasks = []
        for i in range(0, len(chunks), self._chunks_per_task):
            tasks.append(Task(len(tasks), chunks[i:i + self._chunks_per_task]))
        return tasks

    # -- task protocol ------------------------------------------------------

    def get_task(self, pass_id):
        """Lease the next task. Returns (task, None) or (None, error_code)."""
        with self._mu:
            if pass_id < self._cur_pass:
                return None, _Errors.PASS_BEFORE
            if pass_id > self._cur_pass:
                return None, _Errors.PASS_AFTER
            if not self._todo:
                if not self._done and not self._pending:
                    return None, _Errors.ALL_FAILED
                return None, _Errors.NO_MORE_AVAILABLE
            t = self._todo.pop(0)
            t.epoch += 1
            self._pending[t.task_id] = (t, time.time() + self._timeout_s)
            self._snapshot()
            self._ensure_watcher()
            return Task(t.task_id, t.chunks, t.epoch, t.num_failures), None

    def task_finished(self, task_id):
        with self._mu:
            ent = self._pending.pop(task_id, None)
            if ent is None:
                return False
            self._done.append(ent[0])
            rolled = False
            if not self._todo and not self._pending:
                self._next_pass()
                rolled = True
            self._snapshot(force=rolled)
            return True

    def task_failed(self, task_id, epoch=None):
        """Report failure (worker crash detected, bad data...). Requeues the
        task until failure_max, then discards it (service.go:455)."""
        with self._mu:
            ent = self._pending.get(task_id)
            if ent is None:
                return False
            t, _ = ent
            if epoch is not None and epoch != t.epoch:
                return False  # stale report from a previous lease
            del self._pending[task_id]
            t.num_failures += 1
            if t.num_failures >= self._failure_max:
                self._failed.append(t)
            else:
                self._todo.append(t)
            if not self._todo and not self._pending and self._done:
                self._next_pass()
            self._snapshot()
            return True

    def _next_pass(self):
        self._cur_pass += 1
        todo = self._done + self._failed
        for t in todo:
            t.num_failures = 0
        self._todo = sorted(todo, key=lambda t: t.task_id)
        self._done = []
        self._failed = []

    # -- lease timeout watcher (service.go checkTimeoutFunc) ----------------

    def _ensure_watcher(self):
        if self._watcher is None or not self._watcher.is_alive():
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True)
            self._watcher.start()

    def _watch_loop(self):
        while not self._closed.is_set():
            now = time.time()
            with self._mu:
                expired = [
                    (tid, t.epoch) for tid, (t, dl) in self._pending.items()
                    if dl <= now
                ]
                for tid, epoch in expired:
                    self.task_failed(tid, epoch)
                if not self._pending:
                    return  # watcher exits when nothing is leased
            self._closed.wait(min(self._timeout_s / 4.0, 0.25))

    # -- introspection / persistence ----------------------------------------

    def status(self):
        with self._mu:
            return {
                "todo": len(self._todo),
                "pending": len(self._pending),
                "done": len(self._done),
                "failed": len(self._failed),
                "cur_pass": self._cur_pass,
            }

    def _snapshot(self, force=False):
        """Write-throttled persistence: per-lease churn is coalesced (at
        most one write per _snapshot_interval_s); structural transitions
        (dataset set, pass rollover, close) force a write. Bounded
        staleness is the TPU-rebuild trade vs the reference's
        every-mutation etcd write (service.go:207) — on recovery a
        slightly-stale snapshot only re-dispatches already-done tasks."""
        if not self._snapshot_path:
            return
        now = time.time()
        if not force and now - self._last_snapshot < self._snapshot_interval_s:
            self._snapshot_dirty = True
            return
        self._last_snapshot = now
        self._snapshot_dirty = False
        state = {
            "todo": [t.to_json() for t in self._todo],
            "pending": [t.to_json() for t, _ in self._pending.values()],
            "done": [t.to_json() for t in self._done],
            "failed": [t.to_json() for t in self._failed],
            "cur_pass": self._cur_pass,
            "chunks": self._all_chunks,
        }
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._snapshot_path)

    def _recover(self):
        """service.go:166 — a restarted master resumes from the snapshot;
        tasks that were pending at crash time go back to todo."""
        with open(self._snapshot_path) as f:
            state = json.load(f)
        self._todo = [Task.from_json(d) for d in state["todo"]]
        self._todo += [Task.from_json(d) for d in state["pending"]]
        self._done = [Task.from_json(d) for d in state["done"]]
        self._failed = [Task.from_json(d) for d in state["failed"]]
        self._cur_pass = state["cur_pass"]
        self._all_chunks = state["chunks"]

    # -- TCP front-end (JSON lines) -----------------------------------------

    def serve(self, host="127.0.0.1", port=0):
        """Start the TCP endpoint; returns (host, port)."""
        service = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        resp = service._dispatch(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"ok": False, "error": str(e)}
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode("utf-8"))
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        threading.Thread(
            target=self._server.serve_forever, daemon=True).start()
        return self._server.server_address

    def _dispatch(self, req):
        method = req.get("method")
        if method == "get_task":
            task, err = self.get_task(req.get("pass_id", 0))
            if err:
                return {"ok": False, "error": err}
            return {"ok": True, "task": task.to_json()}
        if method == "task_finished":
            return {"ok": self.task_finished(req["task_id"])}
        if method == "task_failed":
            return {"ok": self.task_failed(req["task_id"],
                                           req.get("epoch"))}
        if method == "set_dataset":
            self.set_dataset(req["chunks"])
            return {"ok": True}
        if method == "status":
            return {"ok": True, "status": self.status()}
        return {"ok": False, "error": "unknown method %r" % method}

    def close(self):
        with self._mu:
            if self._snapshot_dirty:
                self._snapshot(force=True)
        self._closed.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class MasterClient(object):
    """Worker-side client (go/master/client.go role): fetch/finish/fail
    tasks over the JSON-lines TCP protocol, with pass tracking."""

    def __init__(self, addr, timeout_s=10.0):
        self._addr = addr
        self._timeout_s = timeout_s
        self._sock = None
        self._rfile = None
        self.pass_id = 0
        # set when the master reports our pass is over (PASS_BEFORE with
        # sync_pass=False); task_reader uses it as the end-of-epoch signal
        self.pass_ended = False

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                self._addr, timeout=self._timeout_s)
            self._rfile = self._sock.makefile("rb")

    def _call(self, **req):
        """One RPC, surviving a master restart: on ConnectionError /
        EOFError / a raw socket error the client reconnects and retries
        ONCE (with the resilience backoff+accounting) before surfacing
        the failure. The master's snapshot/recover path means a restarted
        master answers the retried call with consistent task state; every
        method here is either idempotent (get_task leases a fresh epoch,
        status/set_dataset) or safely re-reportable (task_finished /
        task_failed on an unknown lease returns ok=False, it doesn't
        corrupt)."""
        from paddle_tpu.resilience import retry as _retry

        def once():
            from paddle_tpu.resilience import chaos as _chaos

            if _chaos.ENABLED:
                _chaos.fault("master.call")
            self._connect()
            try:
                self._sock.sendall(
                    (json.dumps(req) + "\n").encode("utf-8"))
                line = self._rfile.readline()
            except OSError:
                self.close()
                raise
            if not line:
                self.close()
                raise ConnectionError("master closed connection")
            return json.loads(line)

        return _retry.call(once, origin="MasterClient._call", retries=1)

    def get_task(self, sync_pass=True):
        """Returns a Task or None. With sync_pass (default), a client
        lagging behind the master's pass fast-forwards and keeps fetching;
        with sync_pass=False it instead sets ``pass_ended`` and returns
        None, so callers get a clean end-of-epoch boundary."""
        resp = self._call(method="get_task", pass_id=self.pass_id)
        if resp.get("ok"):
            return Task.from_json(resp["task"])
        err = resp.get("error")
        if err == _Errors.PASS_BEFORE:
            if sync_pass:
                self.pass_id += 1
                return self.get_task(sync_pass)
            self.pass_ended = True
        elif err == _Errors.ALL_FAILED:
            self.pass_ended = True
        return None

    def next_pass(self):
        """Acknowledge end of epoch: advance to the master's next pass."""
        self.pass_id += 1
        self.pass_ended = False

    def task_finished(self, task_id):
        return self._call(method="task_finished", task_id=task_id).get("ok")

    def task_failed(self, task_id, epoch=None):
        return self._call(
            method="task_failed", task_id=task_id, epoch=epoch).get("ok")

    def status(self):
        return self._call(method="status").get("status")

    def set_dataset(self, chunks):
        return self._call(method="set_dataset", chunks=chunks).get("ok")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None


def task_reader(client, load_chunk, poll_s=0.1, max_polls=600):
    """Fluid-style reader over master-dispatched tasks (client.go's
    paddle.reader.creator.cloud_reader role).

    ``load_chunk(chunk)`` yields samples for one chunk descriptor. Each
    ``reader()`` iteration is ONE pass: it leases tasks until the master
    rolls to the next pass (or every task failed), reporting
    task_finished per completed task and task_failed on a chunk
    exception. Call ``reader()`` again for the next epoch.
    """

    def reader():
        polls = 0
        while True:
            task = client.get_task(sync_pass=False)
            if task is None:
                if client.pass_ended:
                    client.next_pass()  # epoch boundary
                    return
                polls += 1
                if polls >= max_polls:
                    return
                # tasks may still be leased elsewhere; wait for requeue
                time.sleep(poll_s)
                continue
            polls = 0
            try:
                for chunk in task.chunks:
                    for sample in load_chunk(chunk):
                        yield sample
            except Exception:  # noqa: BLE001 - report and move on
                client.task_failed(task.task_id, task.epoch)
                continue
            client.task_finished(task.task_id)

    return reader
