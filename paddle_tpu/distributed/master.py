"""Elastic data-dispatch master: leased tasks, timeout requeue, failure
discard, pass management, crash-recoverable snapshots.

Reference parity: go/master/service.go — ``SetDataset``/``partition``
(:106), ``GetTask`` (:368, lease + timeout timer), ``TaskFinished`` (:411),
``TaskFailed`` (:455, requeue until failure_max then discard), snapshot-to-
store recovery (:166,207) — and go/master/client.go's task-backed reader.

TPU-first differences: the store is a local file (or any object with
save/load) instead of etcd — on Cloud TPU pods the coordinator's disk or
GCS plays that role; the wire protocol is newline-delimited JSON over TCP
instead of Go net/rpc, so Python workers need no extra deps. The trainer
process is stateless: any worker can fetch any task, so killing a worker
mid-epoch only delays its leased tasks until the lease times out and the
task is re-dispatched (the elastic-training contract the reference's
fault-tolerance docs describe).
"""

import json
import os
import socket
import socketserver
import threading
import time

from paddle_tpu.observability import lock_witness

__all__ = [
    "Task", "MasterService", "MasterClient", "task_reader",
    "serve_json_lines", "close_json_server", "JsonConn",
    "JsonLineClient", "ThrottledSnapshot", "AuthError",
]


class AuthError(ValueError):
    """Bad or missing bearer token on an authenticated JSON-lines
    endpoint. A ``ValueError`` subclass on purpose: the resilience
    classifier treats ValueError as permanent, so no retry shell in the
    repo will ever spin on a credential failure — the caller fixes its
    token or stays out."""


# ---------------------------------------------------------------------------
# shared transport + snapshot substrate (also used by elastic/coordinator.py)
# ---------------------------------------------------------------------------


class JsonConn(object):
    """Per-connection context handed to connection-aware dispatchers
    (``serve_json_lines(..., pass_conn=True)``) and to the
    ``on_open``/``on_close`` callbacks. ``state`` is a scratch dict the
    service owns (the serving frontend keys its live streams there so a
    disconnect can tear them down); ``sock``/``rfile`` let a STREAMING
    dispatcher poll the connection for an in-band cancel line or EOF
    while it is producing messages (the client sends nothing else
    mid-stream, so peeking the raw socket is race-free)."""

    __slots__ = ("id", "sock", "rfile", "state")

    def __init__(self, conn_id, sock, rfile):
        self.id = conn_id
        self.sock = sock
        self.rfile = rfile
        self.state = {}


def serve_json_lines(dispatch, host="127.0.0.1", port=0, pass_conn=False,
                     on_open=None, on_close=None, ssl_context=None,
                     auth_token=None):
    """Start a threading TCP endpoint speaking newline-delimited JSON:
    every request line is parsed and handed to ``dispatch(dict) -> dict``
    (or ``dispatch(dict, conn)`` with ``pass_conn=True``); exceptions
    become ``{"ok": False, "error": str(exc)}``. Returns
    ``(server, (host, port))`` — the caller owns shutdown/server_close.
    This is the one wire protocol every control-plane service in the
    repo shares (master task queue, fleet coordinator, serving
    frontend): Python workers need no RPC deps, and a line is a
    complete framed message.

    Streaming responses: when ``dispatch`` returns an ITERATOR (any
    non-dict iterable — a generator, typically) instead of a dict, each
    yielded dict is written as its own line and flushed immediately, so
    a client can consume a response incrementally (the serving
    frontend's token streams). The END of a stream is the dispatcher's
    protocol to mark in-band (a terminal message); an exception raised
    mid-iteration becomes a terminal ``{"ok": False, "error": ...}``
    line, and the iterator is always ``close()``d — abandoning a stream
    because the client disconnected runs the dispatcher's cleanup
    (``finally`` blocks / ``GeneratorExit``), which is how per-stream
    resources get reclaimed.

    ``on_open(conn)`` / ``on_close(conn)`` fire when a connection is
    established / torn down (either side closing), with the same
    :class:`JsonConn` the dispatcher saw — the close callback is the
    disconnect-reclamation hook. Both default to None and the default
    ``pass_conn=False`` keeps the exact one-request/one-response
    contract the master task queue and fleet coordinator were built on.

    Chaos sites (armed only via ``FLAGS_chaos_spec``, zero cost
    otherwise): ``net.accept`` severs a just-accepted connection before
    any request is read; ``net.send`` fails a response write, severing
    the connection mid-(stream) — both exercise client reconnect /
    typed-error paths, never a wedge.

    Transport security (both default off, wire bytes unchanged):
    ``ssl_context`` (an ``ssl.SSLContext`` with a server cert loaded)
    wraps every accepted connection in TLS before the first line is
    read; ``auth_token`` requires every request line to carry a
    matching ``"auth"`` bearer field — a bad or missing token answers
    one typed :class:`AuthError` line and severs the connection, and
    the ``auth`` field is always stripped before dispatch so services
    never see (or log) credentials."""

    class Handler(socketserver.StreamRequestHandler):
        def setup(self):
            # streaming responses are many SMALL line writes in quick
            # succession; Nagle+delayed-ACK batches them into ~20ms-late
            # tails the wire SLOs (ttft, inter-token, trace coverage)
            # would wrongly charge to the server — flush every line now
            try:
                self.request.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # non-TCP transports (tests) don't carry the opt
            socketserver.StreamRequestHandler.setup(self)
            with self.server._conn_mu:
                self.server._live_conns.add(self.connection)
                self.server._next_conn_id += 1
                cid = self.server._next_conn_id
            # ThreadingMixIn owns this thread's construction, so the
            # role name lands here instead of a Thread(name=...) kwarg
            threading.current_thread().name = (
                "paddle-tpu-jsonl-conn-%d" % cid)
            self.ctx = JsonConn(cid, self.connection, self.rfile)
            self._opened = False
            if on_open is not None:
                try:
                    on_open(self.ctx)
                    self._opened = True
                except Exception:  # noqa: BLE001 - service hook, not wire
                    import logging

                    logging.getLogger("paddle_tpu.distributed").exception(
                        "serve_json_lines on_open callback failed")
            else:
                self._opened = True

        def finish(self):
            with self.server._conn_mu:
                self.server._live_conns.discard(self.connection)
            if on_close is not None and self._opened:
                try:
                    on_close(self.ctx)
                except Exception:  # noqa: BLE001 - service hook, not wire
                    import logging

                    logging.getLogger("paddle_tpu.distributed").exception(
                        "serve_json_lines on_close callback failed")
            socketserver.StreamRequestHandler.finish(self)

        def _send(self, resp):
            payload = (json.dumps(resp) + "\n").encode("utf-8")
            if self._chaos.ENABLED:
                self._chaos.fault("net.send")
            self.wfile.write(payload)
            self.wfile.flush()
            with self.server._conn_mu:
                self.server.bytes_sent += len(payload)

        def handle(self):
            # bound once per connection, not per message: _send sits on
            # the per-line streaming hot path
            from paddle_tpu.resilience import chaos as _chaos

            self._chaos = _chaos
            if _chaos.ENABLED:
                try:
                    _chaos.fault("net.accept")
                except Exception:  # noqa: BLE001 - injected accept fault
                    return  # sever: the client sees EOF and reconnects
            try:
                for line in self.rfile:
                    with self.server._conn_mu:
                        self.server.bytes_received += len(line)
                    try:
                        req = json.loads(line)
                        if (isinstance(req, dict)
                                and req.pop("auth", None) != auth_token
                                and auth_token is not None):
                            # one typed refusal, then sever: an
                            # unauthenticated peer gets no second
                            # request on this connection
                            self._send({
                                "ok": False, "etype": "AuthError",
                                "error": "bad or missing auth token"})
                            return
                        resp = (dispatch(req, self.ctx) if pass_conn
                                else dispatch(req))
                    except Exception as e:  # noqa: BLE001
                        resp = {"ok": False, "error": str(e)}
                    if isinstance(resp, dict):
                        self._send(resp)
                        continue
                    # streaming: one line per yielded message, flushed
                    # as produced; a mid-stream dispatcher exception is
                    # delivered as a terminal error line
                    it = iter(resp)
                    try:
                        while True:
                            try:
                                msg = next(it)
                            except StopIteration:
                                break
                            except Exception as e:  # noqa: BLE001
                                self._send({"ok": False, "error": str(e)})
                                break
                            self._send(msg)
                    finally:
                        close = getattr(it, "close", None)
                        if close is not None:
                            close()
            except OSError:
                # severed connection (client gone, close_json_server,
                # or an injected net.send fault): the dispatcher's
                # stream cleanup already ran via the finally above
                return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def get_request(self):
            # TLS wrap at accept time, before the handler thread reads
            # a byte; a failed handshake is an OSError the accept loop
            # already absorbs (the peer just sees a severed socket)
            sock, addr = socketserver.ThreadingTCPServer.get_request(
                self)
            if ssl_context is not None:
                sock = ssl_context.wrap_socket(sock, server_side=True)
            return sock, addr

    server = Server((host, port), Handler)
    server._conn_mu = lock_witness.make_lock("distributed.jsonl.conn")
    server._live_conns = set()
    server._next_conn_id = 0
    server.bytes_sent = 0
    server.bytes_received = 0
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="paddle-tpu-jsonl-accept-%d"
                          % server.server_address[1]).start()
    return server, server.server_address


def close_json_server(server):
    """Full shutdown of a serve_json_lines endpoint: stop accepting,
    close the listener AND sever every established client connection —
    ``server_close`` alone leaves accepted sockets alive, so a
    'restarted' service would keep answering from the dead instance's
    threads and clients would never exercise their reconnect path."""
    if server is None:
        return
    server.shutdown()
    server.server_close()
    with server._conn_mu:
        conns = list(server._live_conns)
        server._live_conns.clear()
    for conn in conns:
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass


def _parse_addr(one):
    """One address spec -> (host, port). Accepts 'host:port' or a
    (host, port) pair."""
    if isinstance(one, str):
        host, _, port = one.rpartition(":")
        return (host or "127.0.0.1", int(port))
    return (one[0], int(one[1]))


class JsonLineClient(object):
    """Shared client shell for the JSON-lines protocol: one persistent
    socket, reconnect-and-retry-once across a service restart (the
    resilience backoff+accounting), per-request chaos site hook. The
    retried call is safe because every service speaking this protocol
    follows the snapshot/recover pattern: a restarted service answers
    with consistent state and unknown-id requests return a typed error
    instead of corrupting.

    ``addr`` may be a single 'host:port' / (host, port), a
    comma-separated 'h:p,h:p' string, or a list of either — with more
    than one address the client fails over: a connect that fails (or a
    send on a severed socket) rotates to the next address, so the
    existing reconnect-retry shells transparently reach a survivor
    (e.g. a router replica) without new retry machinery.

    ``ssl_context`` (client-mode ``ssl.SSLContext``) wraps the socket
    in TLS; ``auth_token`` stamps every request line with the bearer
    ``"auth"`` field an authenticated endpoint demands — a mismatch
    surfaces as the typed, never-retried :class:`AuthError`."""

    #: metrics/blackbox origin for retry accounting; subclasses override
    origin = "JsonLineClient._call"

    def __init__(self, addr, timeout_s=10.0, ssl_context=None,
                 auth_token=None):
        if isinstance(addr, str):
            self._addrs = [_parse_addr(a.strip())
                           for a in addr.split(",") if a.strip()]
        elif (isinstance(addr, (list, tuple)) and len(addr) == 2
                and isinstance(addr[0], str)
                and not isinstance(addr[1], (str, list, tuple))):
            # a bare (host, port) pair, the historical form
            self._addrs = [_parse_addr(addr)]
        else:
            self._addrs = [_parse_addr(a) for a in addr]
        if not self._addrs:
            raise ValueError("JsonLineClient needs at least one address")
        self._addr_i = 0
        self._timeout_s = timeout_s
        self._ssl_context = ssl_context
        self._auth_token = auth_token
        self._sock = None
        self._rfile = None

    @property
    def _addr(self):
        """The address currently targeted (rotates on failover)."""
        return self._addrs[self._addr_i]

    def _chaos_site(self, req):
        """Chaos site to arm for this request (None = uninstrumented)."""
        return None

    def _trace_context(self, req):
        """Trace envelope for this request (None = untraced — the
        default, so the wire bytes of an untracing client are identical
        to pre-tracing builds). ServingClient overrides this to mint a
        request-scoped trace id + send timestamp when
        FLAGS_request_tracing is on (observability/tracing.py); any
        JSON-lines service can adopt the same envelope field."""
        return None

    def _connect(self):
        if self._sock is not None:
            return
        last = None
        for _ in range(len(self._addrs)):
            try:
                sock = socket.create_connection(
                    self._addr, timeout=self._timeout_s)
            except OSError as exc:
                # failover: rotate to the next configured address and
                # let the connect loop (or the caller's retry shell)
                # reach a survivor
                last = exc
                self._addr_i = (self._addr_i + 1) % len(self._addrs)
                continue
            try:  # small-line protocol: never let Nagle sit on a frame
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            if self._ssl_context is not None:
                sock = self._ssl_context.wrap_socket(
                    sock, server_hostname=self._addr[0])
            self._sock = sock
            self._rfile = sock.makefile("rb")
            return
        raise last

    def _send_line(self, req):
        """Connect (if needed) and write one framed request; a send
        failure closes the socket and rotates the target address so the
        next attempt reconnects (to the next replica, if any)."""
        self._connect()
        if self._auth_token is not None and isinstance(req, dict):
            req = dict(req, auth=self._auth_token)
        try:
            self._sock.sendall((json.dumps(req) + "\n").encode("utf-8"))
        except OSError:
            self.close()
            self._addr_i = (self._addr_i + 1) % len(self._addrs)
            raise

    def _recv_line(self):
        """Read one framed response; EOF (the service closed or was
        severed) and socket errors close the socket and raise — both
        are classified transient, so retry shells reconnect."""
        try:
            line = self._rfile.readline()
        except OSError:
            self.close()
            self._addr_i = (self._addr_i + 1) % len(self._addrs)
            raise
        if not line:
            self.close()
            self._addr_i = (self._addr_i + 1) % len(self._addrs)
            raise ConnectionError(
                "%s: service closed connection" % type(self).__name__)
        return json.loads(line)

    def _call(self, **req):
        """One RPC, surviving a service restart: on ConnectionError /
        EOFError / a raw socket error the client reconnects and retries
        ONCE (with the resilience backoff+accounting) before surfacing
        the failure."""
        from paddle_tpu.resilience import retry as _retry

        ctx = self._trace_context(req)
        if ctx is not None:
            req = dict(req, trace=ctx)

        def once():
            from paddle_tpu.resilience import chaos as _chaos

            if _chaos.ENABLED:
                site = self._chaos_site(req)
                if site:
                    _chaos.fault(site)
            self._send_line(req)
            resp = self._recv_line()
            if (isinstance(resp, dict)
                    and resp.get("etype") == "AuthError"):
                self.close()
                raise AuthError(resp.get("error", "auth rejected"))
            return resp

        return _retry.call(once, origin=self.origin, retries=1)

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None


class ThrottledSnapshot(object):
    """Crash-recovery snapshots with the disk write OFF the service
    lock. ``capture(state)`` — called *while holding* the owner's lock —
    only stamps the serialized state into a sequence-numbered pending
    slot (plus the original write throttle: per-mutation churn coalesces
    to one capture per ``interval_s``; ``force=True`` for structural
    transitions). ``flush()`` — called with the owner's lock *released*
    — lands the newest capture atomically (tmp file + rename).

    Two guarantees the old write-under-the-lock scheme lacked:

    * an RPC (a heartbeat, a get_task) never waits behind a slow
      ``json.dump``+disk write happening under the service mutex — the
      serialization and IO run on whichever thread calls flush, lock
      free;
    * commits are sequence-ordered: a slow stale writer racing a newer
      one loses (its tmp file is discarded), so the *final* capture —
      e.g. the forced one in ``close()`` — can never be clobbered by an
      older in-flight write persisting a task as ``todo`` that is
      actually leased or done.
    """

    def __init__(self, path, interval_s=0.5):
        self.path = path
        self.interval_s = float(interval_s)
        self._mu = lock_witness.make_lock(
            "distributed.snapshot.throttle")  # pending/seq bookkeeping only
        self._pending = None         # (seq, state): newest unflushed capture
        self._seq = 0
        self._written_seq = 0
        self._last_capture = 0.0
        self.dirty = False           # a throttled-away capture is owed

    def capture(self, state, force=False):
        """``state`` may be the state dict itself or a zero-arg callable
        producing it — pass the callable from per-mutation hot paths, so
        a throttled-away capture costs a clock read, not an O(n) state
        serialization under the owner's lock."""
        if not self.path:
            return
        with self._mu:
            now = time.time()
            if (not force
                    and now - self._last_capture < self.interval_s):
                self.dirty = True
                return
            self._last_capture = now
            self.dirty = False
            self._seq += 1
            self._pending = (self._seq,
                             state() if callable(state) else state)

    def flush(self):
        """Write the newest pending capture; a no-op when none. Never
        call while holding the owner's service lock (defeats the point).
        """
        if not self.path:
            return
        with self._mu:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        seq, state = pending
        tmp = "%s.tmp-%d-%d" % (self.path, os.getpid(), seq)
        with open(tmp, "w") as f:
            json.dump(state, f)
        stale = None
        with self._mu:
            # the rename commits under the bookkeeping lock (it is an
            # atomic metadata op, unlike the dump above): seq order is
            # decided and acted on indivisibly, so a paused stale writer
            # can never replace a newer snapshot after losing the check
            if seq > self._written_seq:
                self._written_seq = seq
                os.replace(tmp, self.path)
            else:
                stale = tmp
        if stale:
            try:
                os.unlink(stale)
            except OSError:
                pass

    def load(self):
        """Parsed snapshot state, or None. A MISSING file is a normal
        cold start (silent); an existing-but-unreadable one is a loud
        event — it is quarantined (``.corrupt-<n>``, kept for autopsy,
        the checkpoint-layer discipline) and logged, because a service
        silently coming up empty is indistinguishable from data loss."""
        if not self.path or not os.path.exists(self.path):
            return None
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError) as exc:
            n = 0
            dst = "%s.corrupt-%d" % (self.path, n)
            while os.path.exists(dst):
                n += 1
                dst = "%s.corrupt-%d" % (self.path, n)
            try:
                os.replace(self.path, dst)
            except OSError:
                dst = None
            import logging

            logging.getLogger("paddle_tpu.distributed").warning(
                "snapshot %s exists but is unreadable (%s); quarantined "
                "to %s — the service recovers NOTHING and starts empty",
                self.path, exc, dst)
            return None


class Task(object):
    __slots__ = ("task_id", "chunks", "epoch", "num_failures")

    def __init__(self, task_id, chunks, epoch=0, num_failures=0):
        self.task_id = task_id
        self.chunks = list(chunks)
        self.epoch = epoch
        self.num_failures = num_failures

    def to_json(self):
        return {
            "task_id": self.task_id,
            "chunks": self.chunks,
            "epoch": self.epoch,
            "num_failures": self.num_failures,
        }

    @staticmethod
    def from_json(d):
        return Task(d["task_id"], d["chunks"], d["epoch"], d["num_failures"])


class _Errors(object):
    PASS_BEFORE = "pass_before"
    PASS_AFTER = "pass_after"
    NO_MORE_AVAILABLE = "no_more_available"
    ALL_FAILED = "all_task_failed"


class MasterService(object):
    """In-process task-queue service; optionally served over TCP."""

    def __init__(self, chunks_per_task=1, timeout_s=5.0, failure_max=3,
                 snapshot_path=None, snapshot_interval_s=0.5):
        """snapshot_interval_s: write-throttle window for per-lease
        snapshot churn (see _snapshot); structural transitions always
        force a write. Crash-recovery tests raise it to pin exactly
        which state a simulated kill -9 loses."""
        self._chunks_per_task = max(1, int(chunks_per_task))
        self._timeout_s = timeout_s
        self._failure_max = failure_max
        self._snapshot_path = snapshot_path
        self._mu = lock_witness.make_rlock("distributed.master")
        self._todo = []  # [Task]
        self._pending = {}  # task_id -> (Task, lease_deadline)
        self._done = []
        self._failed = []
        self._cur_pass = 0
        self._all_chunks = []
        self._server = None
        self._watcher = None
        self._closed = threading.Event()
        self._snap = ThrottledSnapshot(snapshot_path,
                                       interval_s=snapshot_interval_s)
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset / partition (service.go:106,280) ---------------------------

    def set_dataset(self, chunks):
        """chunks: list of opaque JSON-serializable chunk descriptors (file
        paths, (file, offset) pairs...). Partitioned chunks_per_task each."""
        with self._mu:
            self._all_chunks = list(chunks)
            if not self._todo and not self._pending and not self._done:
                self._todo = self._partition(self._all_chunks)
                self._snapshot(force=True)
        self._snap.flush()

    def _partition(self, chunks):
        tasks = []
        for i in range(0, len(chunks), self._chunks_per_task):
            tasks.append(Task(len(tasks), chunks[i:i + self._chunks_per_task]))
        return tasks

    # -- task protocol ------------------------------------------------------

    def get_task(self, pass_id):
        """Lease the next task. Returns (task, None) or (None, error_code)."""
        with self._mu:
            if pass_id < self._cur_pass:
                return None, _Errors.PASS_BEFORE
            if pass_id > self._cur_pass:
                return None, _Errors.PASS_AFTER
            if not self._todo:
                if not self._done and not self._pending:
                    return None, _Errors.ALL_FAILED
                return None, _Errors.NO_MORE_AVAILABLE
            t = self._todo.pop(0)
            t.epoch += 1
            self._pending[t.task_id] = (t, time.time() + self._timeout_s)
            self._snapshot()
            self._ensure_watcher()
            leased = Task(t.task_id, t.chunks, t.epoch, t.num_failures)
        self._snap.flush()
        return leased, None

    def task_finished(self, task_id):
        with self._mu:
            ent = self._pending.pop(task_id, None)
            if ent is not None:
                self._done.append(ent[0])
                rolled = False
                if not self._todo and not self._pending:
                    self._next_pass()
                    rolled = True
                self._snapshot(force=rolled)
        self._snap.flush()
        return ent is not None

    def task_failed(self, task_id, epoch=None):
        """Report failure (worker crash detected, bad data...). Requeues the
        task until failure_max, then discards it (service.go:455)."""
        with self._mu:
            ok = self._task_failed_locked(task_id, epoch)
        self._snap.flush()
        return ok

    def _task_failed_locked(self, task_id, epoch):
        ent = self._pending.get(task_id)
        if ent is None:
            return False
        t, _ = ent
        if epoch is not None and epoch != t.epoch:
            return False  # stale report from a previous lease
        del self._pending[task_id]
        t.num_failures += 1
        if t.num_failures >= self._failure_max:
            self._failed.append(t)
        else:
            self._todo.append(t)
        if not self._todo and not self._pending and self._done:
            self._next_pass()
        self._snapshot()
        return True

    def _next_pass(self):
        self._cur_pass += 1
        todo = self._done + self._failed
        for t in todo:
            t.num_failures = 0
        self._todo = sorted(todo, key=lambda t: t.task_id)
        self._done = []
        self._failed = []

    # -- lease timeout watcher (service.go checkTimeoutFunc) ----------------

    def _ensure_watcher(self):
        if self._watcher is None or not self._watcher.is_alive():
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="paddle-tpu-master-lease-watch")
            self._watcher.start()

    def _watch_loop(self):
        while not self._closed.is_set():
            now = time.time()
            with self._mu:
                expired = [
                    (tid, t.epoch) for tid, (t, dl) in self._pending.items()
                    if dl <= now
                ]
            # fail the leases via the PUBLIC method, outside our own lock
            # hold: it re-validates (pending membership + epoch) under the
            # lock and flushes the snapshot off-lock
            for tid, epoch in expired:
                self.task_failed(tid, epoch)
            with self._mu:
                if not self._pending:
                    # exit decision and watcher-slot release are ONE
                    # atomic step: a lease taken after this point sees
                    # the slot empty and _ensure_watcher spawns a fresh
                    # watcher instead of trusting this dying thread
                    if self._watcher is threading.current_thread():
                        self._watcher = None
                    return
            self._closed.wait(min(self._timeout_s / 4.0, 0.25))

    # -- introspection / persistence ----------------------------------------

    def status(self):
        with self._mu:
            return {
                "todo": len(self._todo),
                "pending": len(self._pending),
                "done": len(self._done),
                "failed": len(self._failed),
                "cur_pass": self._cur_pass,
            }

    def _snapshot(self, force=False):
        """Capture-only persistence (call with ``_mu`` held): the state
        dict is stamped into the ThrottledSnapshot's pending slot —
        per-lease churn coalesced to one capture per interval, structural
        transitions (dataset set, pass rollover, close) forced — and the
        actual ``json.dump`` + disk write happens in ``_snap.flush()``
        AFTER the caller releases ``_mu``, so concurrent RPCs never queue
        behind the serialization work. Bounded staleness is the
        TPU-rebuild trade vs the reference's every-mutation etcd write
        (service.go:207) — on recovery a slightly-stale snapshot only
        re-dispatches already-done tasks."""
        self._snap.capture(lambda: {
            "todo": [t.to_json() for t in self._todo],
            "pending": [t.to_json() for t, _ in self._pending.values()],
            "done": [t.to_json() for t in self._done],
            "failed": [t.to_json() for t in self._failed],
            "cur_pass": self._cur_pass,
            "chunks": self._all_chunks,
        }, force=force)

    def _recover(self):
        """service.go:166 — a restarted master resumes from the snapshot;
        tasks that were pending at crash time go back to todo."""
        state = self._snap.load()
        if state is None:
            return
        self._todo = [Task.from_json(d) for d in state["todo"]]
        self._todo += [Task.from_json(d) for d in state["pending"]]
        self._done = [Task.from_json(d) for d in state["done"]]
        self._failed = [Task.from_json(d) for d in state["failed"]]
        self._cur_pass = state["cur_pass"]
        self._all_chunks = state["chunks"]

    # -- TCP front-end (JSON lines) -----------------------------------------

    def serve(self, host="127.0.0.1", port=0):
        """Start the TCP endpoint; returns (host, port)."""
        self._server, addr = serve_json_lines(self._dispatch, host, port)
        return addr

    def _dispatch(self, req):
        method = req.get("method")
        if method == "get_task":
            task, err = self.get_task(req.get("pass_id", 0))
            if err:
                return {"ok": False, "error": err}
            return {"ok": True, "task": task.to_json()}
        if method == "task_finished":
            return {"ok": self.task_finished(req["task_id"])}
        if method == "task_failed":
            return {"ok": self.task_failed(req["task_id"],
                                           req.get("epoch"))}
        if method == "set_dataset":
            self.set_dataset(req["chunks"])
            return {"ok": True}
        if method == "status":
            return {"ok": True, "status": self.status()}
        return {"ok": False, "error": "unknown method %r" % method}

    def close(self):
        with self._mu:
            if self._snap.dirty:
                self._snapshot(force=True)
        # the final flush is sequence-ordered: even if an older capture's
        # write is still in flight on another thread, this newest state
        # wins — close() can never leave a leased/done task persisted in
        # a stale 'todo' position
        self._snap.flush()
        self._closed.set()
        close_json_server(self._server)
        self._server = None


class MasterClient(JsonLineClient):
    """Worker-side client (go/master/client.go role): fetch/finish/fail
    tasks over the JSON-lines TCP protocol, with pass tracking.

    Every ``_call`` survives a master restart (reconnect-and-retry-once,
    inherited from :class:`JsonLineClient`): the master's snapshot/
    recover path means a restarted master answers the retried call with
    consistent task state, and every method here is either idempotent
    (get_task leases a fresh epoch, status/set_dataset) or safely
    re-reportable (task_finished / task_failed on an unknown lease
    returns ok=False, it doesn't corrupt)."""

    origin = "MasterClient._call"

    def __init__(self, addr, timeout_s=10.0):
        super(MasterClient, self).__init__(addr, timeout_s=timeout_s)
        self.pass_id = 0
        # set when the master reports our pass is over (PASS_BEFORE with
        # sync_pass=False); task_reader uses it as the end-of-epoch signal
        self.pass_ended = False

    def _chaos_site(self, req):
        return "master.call"

    def get_task(self, sync_pass=True):
        """Returns a Task or None. With sync_pass (default), a client
        lagging behind the master's pass fast-forwards and keeps fetching;
        with sync_pass=False it instead sets ``pass_ended`` and returns
        None, so callers get a clean end-of-epoch boundary."""
        resp = self._call(method="get_task", pass_id=self.pass_id)
        if resp.get("ok"):
            return Task.from_json(resp["task"])
        err = resp.get("error")
        if err == _Errors.PASS_BEFORE:
            if sync_pass:
                self.pass_id += 1
                return self.get_task(sync_pass)
            self.pass_ended = True
        elif err == _Errors.ALL_FAILED:
            self.pass_ended = True
        return None

    def next_pass(self):
        """Acknowledge end of epoch: advance to the master's next pass."""
        self.pass_id += 1
        self.pass_ended = False

    def task_finished(self, task_id):
        return self._call(method="task_finished", task_id=task_id).get("ok")

    def task_failed(self, task_id, epoch=None):
        return self._call(
            method="task_failed", task_id=task_id, epoch=epoch).get("ok")

    def status(self):
        return self._call(method="status").get("status")

    def set_dataset(self, chunks):
        return self._call(method="set_dataset", chunks=chunks).get("ok")


def task_reader(client, load_chunk, poll_s=0.1, max_polls=600):
    """Fluid-style reader over master-dispatched tasks (client.go's
    paddle.reader.creator.cloud_reader role).

    ``load_chunk(chunk)`` yields samples for one chunk descriptor. Each
    ``reader()`` iteration is ONE pass: it leases tasks until the master
    rolls to the next pass (or every task failed), reporting
    task_finished per completed task and task_failed on a chunk
    exception. Call ``reader()`` again for the next epoch.
    """

    def reader():
        polls = 0
        while True:
            task = client.get_task(sync_pass=False)
            if task is None:
                if client.pass_ended:
                    client.next_pass()  # epoch boundary
                    return
                polls += 1
                if polls >= max_polls:
                    return
                # tasks may still be leased elsewhere; wait for requeue
                time.sleep(poll_s)
                continue
            polls = 0
            try:
                for chunk in task.chunks:
                    for sample in load_chunk(chunk):
                        yield sample
            except Exception:  # noqa: BLE001 - report and move on
                client.task_failed(task.task_id, task.epoch)
                continue
            client.task_finished(task.task_id)

    return reader
