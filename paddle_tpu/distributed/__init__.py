"""Elastic / fault-tolerant training services (go/master + go/pserver
capability surface, rebuilt for TPU pods)."""

from paddle_tpu.distributed.master import (  # noqa: F401
    MasterClient,
    MasterService,
    Task,
    task_reader,
)
