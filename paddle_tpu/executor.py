"""Executor: run Programs on a Place — by whole-program XLA compilation.

Reference parity: python/paddle/fluid/executor.py:374 (Executor.run feeds
numpy -> tensors, fetches back) + paddle/fluid/framework/executor.cc:163.
The TPU-first difference: instead of a sequential per-op interpreter loop
(executor.cc:392-404), ``run`` traces block 0 through the op lowerings into
one JAX function, jit-compiles it per (program version, feed shapes, fetch
set) — cached like the reference's ``use_program_cache`` — and executes a
single fused XLA program per step. Persistable vars (params, optimizer
state, BN stats) live in the Scope as device arrays and are threaded
through the step function with buffer donation (in-place semantics without
mutation).
"""

import threading
import time
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu import framework
from paddle_tpu import profiler as _profiler
from paddle_tpu.core import exec_cache
from paddle_tpu.observability import blackbox as _blackbox
from paddle_tpu.observability import lock_witness as _lock_witness
from paddle_tpu.resilience import chaos as _chaos
from paddle_tpu.resilience import retry as _retry
from paddle_tpu.observability import explain as _explain
from paddle_tpu.observability import memory as _memory
from paddle_tpu.observability import step_profiler as _stepprof
from paddle_tpu.observability import telemetry as _telemetry
from paddle_tpu.core.fingerprint import (
    executable_key,
    program_fingerprint,
    trace_flags_key,
)
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.lowering import CompiledProgram
from paddle_tpu.core.scope import Scope
from paddle_tpu.core.types import Place, TPUPlace, np_dtype

_global_scope = Scope()
_scope_stack = [_global_scope]

# Process-global executable registry. Keys are content-addressed
# (core/fingerprint.py), so structurally identical programs share ONE
# compile across Executor instances, scopes with identical var-name
# signatures, and Predictor.Clone() serving threads — where the old
# id(program)/id(scope) keys forced a recompile per instance (and could
# alias a dead program's reused id() to a live one after GC). LRU-bounded:
# eviction drops only the shared handle; executors that already hold an
# entry in their instance cache keep using it.
_shared_executables = OrderedDict()
_shared_lock = _lock_witness.make_lock("executor.shared_executables")
_SHARED_CAP = 128


def global_scope():
    """The scope Executor.run defaults to. Like the reference's
    ``fluid.global_scope()`` / ``scope_guard`` pair (executor.py:g_scope),
    ``scope_guard`` swaps what this returns for the duration of the guard."""
    return _scope_stack[-1]


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        _scope_stack.append(scope)
        try:
            yield
        finally:
            _scope_stack.pop()

    return guard()


def _as_feed_array(value, place):
    """numpy / LoDTensor / device array -> (array, lod or None). Device
    arrays (a double-buffered PyReader's prefetched feeds) pass through
    untouched — np.asarray would block on the in-flight transfer and
    round-trip the data through the host."""
    if isinstance(value, LoDTensor):
        # .numpy() IS the backing ndarray; re-wrapping it in np.asarray
        # added a per-feed copy whenever the holder wasn't already a plain
        # contiguous ndarray — pass it through untouched instead
        return value.numpy(), value.lod() or None
    if isinstance(value, jax.Array):
        return value, None
    return np.asarray(value), None


def _materialize_fetches(arrays, origin):
    """Host-materialize fetched device arrays. With async dispatch the
    allocator's RESOURCE_EXHAUSTED often surfaces at the first host read
    rather than inside the dispatch call, so every materialize site —
    sync return, multi-step return, FetchHandle.result — routes through
    the same M001 enrichment as the dispatch path."""
    try:
        return [np.asarray(a) for a in arrays]
    except Exception as exc:
        if _memory.is_oom(exc) and not isinstance(
                exc, _memory.MemoryExhaustedError):
            _memory.enrich_and_raise(exc, origin=origin)
        raise


def _maybe_verify(program, feed_specs, fetch_names, origin):
    """FLAGS_verify_program gate: run the structural verifier with the
    concrete feed shapes (resolving deferred shape inference) before a
    fresh compile. Raises analysis.ProgramVerifyError on error-severity
    findings; warnings go to the analysis logger."""
    from paddle_tpu import flags as _flags

    if not _flags.get("verify_program"):
        return
    import logging

    from paddle_tpu.analysis import check_program

    diags = check_program(
        program, level="error", fetch_names=fetch_names,
        feed_shapes={n: s for n, (s, _d) in feed_specs.items()},
        origin=origin)
    if diags:
        logging.getLogger("paddle_tpu.analysis").info(
            "verify (%s): %d non-error diagnostic(s): %s", origin,
            len(diags), "; ".join(str(d) for d in diags[:5]))


# On-device finiteness scan for FLAGS_check_nan_inf: one fused executable
# of lax reductions per value-list structure; only the [n] bool vector
# crosses to the host, never the checked values.
_finite_stack = jax.jit(
    lambda vals: jnp.stack([jnp.all(jnp.isfinite(v)) for v in vals])
)


class FetchTimeoutError(RuntimeError):
    """``FetchHandle.result(timeout=...)`` expired before the fetches
    materialized. The handle itself is untouched: nothing was consumed,
    so a later ``result()`` (with or without a timeout) still returns
    the full values — the serving deadline path rejects the REQUEST,
    not the computation."""

    def __init__(self, timeout, fetch_names):
        super(FetchTimeoutError, self).__init__(
            "async fetch of %s did not materialize within %.3fs"
            % (list(fetch_names), timeout))
        self.timeout = timeout
        self.fetch_names = list(fetch_names)


class FetchHandle(object):
    """Live results of an async dispatch (``Executor.run_async``).

    The fetched values are in-flight device arrays; the handle never
    forces a host sync until asked:

      ``arrays()``             the live device arrays (non-blocking)
      ``done()``               True when every fetch has materialized
      ``block_until_ready()``  wait on device completion, no transfer
      ``result()``             numpy values (blocks; memoized) — matches
                               the equivalent ``run(...)`` bit-for-bit
      ``result(timeout=s)``    same, but raise :class:`FetchTimeoutError`
                               (leaving the handle reusable) if the
                               device work isn't done within ``s`` —
                               the deadline primitive the batching
                               server builds on, independent of the
                               watchdog
    """

    def __init__(self, arrays, fetch_names, nan_check=None, track=None,
                 t_dispatch=None, mem_device=None):
        self._arrays = list(arrays)
        self.fetch_names = list(fetch_names)
        self._nan_check = nan_check
        self._numpy = None
        # observability, all None on the undisturbed hot path: _track is
        # the profiler's async-span record, _t_dispatch the telemetry
        # dispatch timestamp, _mem_device the ledger label whose
        # 'activation' entries this handle releases at materialize
        # (all set only when their subsystem was ENABLED)
        self._track = track
        self._t_dispatch = t_dispatch
        self._mem_device = mem_device

    def __len__(self):
        return len(self._arrays)

    def arrays(self):
        return list(self._arrays)

    def done(self):
        for a in self._arrays:
            is_ready = getattr(a, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
        return True

    def block_until_ready(self):
        for a in self._arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()
        return self

    def result(self, timeout=None):
        if self._numpy is None and timeout is not None:
            # Poll, don't block: jax arrays expose readiness but no timed
            # wait, and a blocking block_until_ready() here would make the
            # timeout a lie exactly when it matters (a wedged device).
            # Nothing is consumed before the readiness check, so a timed-
            # out handle can be asked again.
            deadline = time.monotonic() + float(timeout)
            pause = 5e-4
            while not self.done():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise FetchTimeoutError(float(timeout),
                                            self.fetch_names)
                time.sleep(min(pause, remaining))
                pause = min(pause * 2, 0.05)
        if self._numpy is None:
            # a fetch that never materializes is the canonical silent
            # hang (wedged tunnel, dead peer): the guard arms the
            # watchdog so a stall here is named in the black box
            with _blackbox.guard("FetchHandle.result"):
                if self._nan_check is not None:
                    # disarm only AFTER a clean pass: a caller that catches
                    # the NaN error and retries must get the error again,
                    # not the bad values
                    self._nan_check()
                    self._nan_check = None
                track = self._track
                if track is not None:
                    # split device-ready from host-transfer for the trace:
                    # block first (marks "ready"), then materialize
                    self.block_until_ready()
                    _profiler.async_fetch_ready(track)
                self._numpy = _materialize_fetches(
                    self._arrays, "FetchHandle.result")
                if track is not None:
                    _profiler.async_fetch_end(track)
                if self._mem_device is not None:
                    # the device copies of the fetches are released once
                    # numpy is in hand — balance the dispatch-time entries
                    _memory.drop_fetches(self.fetch_names,
                                         self._mem_device)
                    self._mem_device = None
                if self._t_dispatch is not None:
                    _telemetry.record_fetch_materialize(
                        time.perf_counter() - self._t_dispatch)
        return self._numpy


class Executor(object):
    def __init__(self, place=None):
        self.place = place if place is not None else TPUPlace()
        if not isinstance(self.place, Place):
            raise TypeError("place must be a Place (TPUPlace()/CPUPlace())")
        self._cache = {}
        self._run_counter = 0
        self._base_seed = np.random.randint(0, 2**31 - 1)

    # -- compilation cache --------------------------------------------------
    def _get_compiled(self, program, feed_specs, fetch_names, scope,
                      refresh=False):
        # Deferred shape inference must resolve BEFORE the fingerprint is
        # taken: filling shapes afterwards would change the content hash
        # and bust this very cache on the next run. No-op unless the
        # program still carries deferrals (reader pipelines).
        if getattr(program, "_deferred_infer", None):
            program.infer_deferred_shapes(
                feed_shapes={n: s for n, (s, _d) in feed_specs.items()})
        scope_names = self._scope_names(scope)
        device = self.place.jax_device()
        key = (
            # content hash, not id(program): CPython reuses id() after GC,
            # and structurally identical programs should share the compile
            program_fingerprint(program),
            tuple(sorted((n, s, d) for n, (s, d) in feed_specs.items())),
            tuple(fetch_names),
            # Scope contents shape the step signature (state_in): a var
            # initialized later (e.g. startup program ran) must recompile;
            # the NAME SET is the signature, so scopes holding the same
            # vars share executables (not id(scope))
            frozenset(scope_names),
            program._is_test,
            getattr(program, "_amp_dtype", None),
            # trace-time flags alter the lowered computation; toggling one
            # must recompile, not reuse the stale executable
            trace_flags_key(),
            (device.platform, device.id),
        )
        cp = None if refresh else self._cache.get(key)
        if cp is not None:
            exec_cache.record_trace_hit()
            return cp
        with _shared_lock:
            # refresh (use_program_cache=False) bypasses the lookup so
            # THIS run re-traces, but still publishes the fresh compile —
            # evicting instead would yank a live executable out from
            # under unrelated executors / Predictor clones
            cp = None if refresh else _shared_executables.get(key)
            if cp is None:
                exec_cache.record_trace_miss()
                exec_cache.configure()
                # FLAGS_verify_program: structural verification on the
                # fresh-compile path only (never per step) — a bad graph
                # fails here with rule-tagged diagnostics instead of an
                # eval_shape traceback inside CompiledProgram
                _maybe_verify(program, feed_specs, fetch_names,
                              origin="Executor.run")
                # one structured "why did this retrace" event per fresh
                # compile, diffed against the nearest cached key
                _explain.record_compile({
                    "program": key[0],
                    "feed_specs": tuple(sorted(
                        (n, (s, d)) for n, (s, d) in feed_specs.items())),
                    "fetch_names": tuple(fetch_names),
                    "scope_signature": key[3],
                    "flags": key[6],
                    "device": "%s:%d" % (device.platform, device.id),
                    "mode": "single",
                }, forced=refresh)
                def _build():
                    if _chaos.ENABLED:
                        _chaos.fault("exec.compile")
                    return CompiledProgram(
                        program,
                        feed_specs,
                        fetch_names,
                        scope_names,
                        is_test=program._is_test,
                        device=device,
                    )

                # classified-transient failures on the fresh-compile
                # path (flaky cache reads, preempted backend compiles)
                # retry under FLAGS_dispatch_retries; verifier/user
                # errors surface immediately
                cp = _retry.call(_build, origin="Executor.compile")
                # stable cross-process key for the on-disk AOT image
                # layer; device.id included so executors pinned to
                # different local devices never share one baked image
                cp._exec_cache_key = executable_key(
                    program, feed_specs, fetch_names, scope_names,
                    extra=("single", device.platform, device.id,
                           getattr(device, "device_kind", "")),
                )
                _shared_executables[key] = cp
                while len(_shared_executables) > _SHARED_CAP:
                    _shared_executables.popitem(last=False)
            else:
                _shared_executables.move_to_end(key)
                exec_cache.record_trace_hit()
        self._cache[key] = cp
        return cp

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        program = program or framework.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        device = self.place.jax_device()
        if not use_program_cache:
            # reference use_program_cache=False semantics: drop this
            # program's cached single-run executables from THIS executor
            # so this run re-traces; the process-global registry is
            # bypassed (not purged) via refresh — see _get_compiled
            # (multi-step scan executables are keyed separately and
            # survive — they are expensive compiles run() never uses)
            fp = program_fingerprint(program)
            self._cache = {
                k: v for k, v in self._cache.items()
                if k[0] == "multi" or k[0] != fp
            }
        # Everything below (feed transfer, key creation, dispatch) stays on
        # the Place's device: with several backends loaded (TPU plugin +
        # CPU), stray ops like PRNGKey would otherwise run on the default
        # platform — wrong device, and unsafe under concurrent serving.
        with jax.default_device(device):
            return self._run_on_device(
                program, feed, fetch_list, scope, device, return_numpy,
                refresh_cache=not use_program_cache,
            )

    # -- shared run plumbing -------------------------------------------------
    def _prepare_feeds(self, program, feed, device):
        """numpy/LoDTensor feeds -> (device arrays, (shape, dtype) specs),
        cast to the declared var dtype when compatible."""
        feeds = {}
        feed_specs = {}
        for name, value in feed.items():
            arr, _lod = _as_feed_array(value, self.place)
            var = program.global_block()._find_var_recursive(name)
            if (var is not None and var.dtype
                    and arr.dtype != np_dtype(var.dtype)):
                if np.issubdtype(arr.dtype, np.floating) or np.issubdtype(
                    arr.dtype, np.integer
                ):
                    arr = arr.astype(np_dtype(var.dtype))
            feeds[name] = jax.device_put(arr, device)
            feed_specs[name] = (tuple(arr.shape), str(arr.dtype))
        return feeds, feed_specs

    @staticmethod
    def _scope_names(scope):
        names = set()
        s = scope
        while s is not None:
            names.update(s.local_var_names())
            s = s._parent
        return names

    @staticmethod
    def _gather_state(state_in, scope, device):
        state = {}
        for n in state_in:
            v = scope.find_var(n)
            if v is None or v.value is None:
                raise RuntimeError(
                    "persistable variable %r is not initialized in the scope "
                    "(did you run the startup program?)" % n
                )
            val = v.value
            if not isinstance(val, jax.Array):
                val = jax.device_put(np.asarray(val), device)
            elif val.sharding.device_set != {device}:
                # Scope value lives on another Place's device (e.g. trained
                # on TPU, now serving on CPU): move it once.
                val = jax.device_put(val, device)
            state[n] = val
        return state

    def _step_key(self, program):
        self._run_counter += 1
        return jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed or self._base_seed),
            self._run_counter,
        )

    @staticmethod
    def _dispatch(cp, state, feeds, key, origin="Executor.dispatch"):
        """The XLA dispatch, under the resilience shell: the chaos
        ``exec.dispatch`` kill-point fires first (so injected faults are
        indistinguishable from real transient ones), and with
        ``FLAGS_dispatch_retries`` set, classified-transient failures
        back off and retry — vetoed the moment a failed attempt has
        already consumed the donated state buffers (retrying would crash
        on deleted arrays and mask the real error). Both subsystems off:
        two module-bool/flag reads around the plain call. A
        RESOURCE_EXHAUSTED/OOM escaping any path — deterministic, so
        never retried — is upgraded to the M001 diagnostic (black-box
        dump with the ledger's top holders + the predicted peak) on the
        way out; one substring check, paid only on the failure path."""
        chaos_on = _chaos.ENABLED
        if _lock_witness.ENABLED:
            # a witnessed lock held right now spans this device dispatch
            _lock_witness.note_dispatch()
        try:
            if not _retry.retries_enabled():
                if chaos_on:
                    _chaos.fault("exec.dispatch")
                return cp(state, feeds, key)

            def _run():
                if chaos_on:
                    _chaos.fault("exec.dispatch")
                return cp(state, feeds, key)

            return _retry.call(_run, origin=origin, donated=state)
        except Exception as exc:
            if _memory.is_oom(exc) and not isinstance(
                    exc, _memory.MemoryExhaustedError):
                _memory.enrich_and_raise(exc, origin=origin)
            raise

    @staticmethod
    def _nan_check_start(new_state, fetch_names, fetches):
        """FLAGS_check_nan_inf (operator.cc:754) in two phases: the scan
        is an on-device lax reduction fused into one tiny executable,
        DISPATCHED NOW — while the checked arrays are still live; a later
        step may donate these very buffers — and only an [n] bool vector
        crosses to the host when the returned ``finish`` callable runs
        (the old implementation np.asarray'd EVERY output, a full host
        transfer + sync per checked run). Returns None when the flag is
        off."""
        from paddle_tpu import flags as _flags

        if not _flags.get("check_nan_inf"):
            return None
        names, vals, host_bad = [], [], None
        for name, val in list(new_state.items()) + list(
            zip(fetch_names, fetches)
        ):
            if isinstance(val, jax.Array) and jnp.issubdtype(
                val.dtype, jnp.floating
            ):
                names.append(name)
                vals.append(val)
                continue
            arr = np.asarray(val)  # host-side values (rare): check directly
            if host_bad is None and np.issubdtype(
                arr.dtype, np.floating
            ) and not np.all(np.isfinite(arr)):
                host_bad = name
        flags_dev = _finite_stack(vals) if vals else None

        def finish():
            if host_bad is not None:
                raise RuntimeError(
                    "NaN/Inf detected in variable %r after program run "
                    "(FLAGS_check_nan_inf)" % host_bad
                )
            if flags_dev is None:
                return
            finite = np.asarray(flags_dev)
            if not finite.all():
                bad = names[int(np.argmin(finite))]
                raise RuntimeError(
                    "NaN/Inf detected in variable %r after program run "
                    "(FLAGS_check_nan_inf)" % bad
                )

        return finish

    @staticmethod
    def _check_nan_inf(new_state, fetch_names, fetches):
        finish = Executor._nan_check_start(new_state, fetch_names, fetches)
        if finish is not None:
            finish()

    @staticmethod
    def _nan_snapshot(cp, state):
        """Pre-step snapshot for the NaN-provenance replay: the step is
        pure, so (state, feeds, key) reproduce it exactly — but dispatch
        DONATES the mutable state buffers, so those are copied on device
        first (frozen state and feeds survive by reference). None unless
        both FLAGS_check_nan_inf and FLAGS_nan_provenance are on."""
        from paddle_tpu import flags as _flags

        if not (_flags.get("check_nan_inf")
                and _flags.get("nan_provenance")):
            return None
        snap = {n: state[n] for n in cp.frozen_state}
        for n in cp.mutable_state:
            v = state[n]
            snap[n] = jnp.array(v, copy=True) if isinstance(
                v, jax.Array) else v
        return snap

    @staticmethod
    def _nan_blame(exc, program, snapshot, feeds, key, device, steps=1,
                   mutable_state=(), multi=False):
        """The scanner tripped: replay from the snapshot and raise the
        enriched NonFiniteError naming the first bad op; without a
        snapshot (provenance off) the plain scanner error passes
        through. ``multi`` routes through the scan-body replay (per-step
        fold_in keys) even for steps == 1."""
        if snapshot is None:
            raise exc
        from paddle_tpu.observability import nan_provenance as _nanprov

        _nanprov.enrich_and_raise(
            exc, program, snapshot, feeds, key, steps=steps,
            mutable_state=mutable_state, is_test=program._is_test,
            platform=getattr(device, "platform", None), multi=multi)

    def _run_on_device(self, program, feed, fetch_list, scope, device,
                       return_numpy, as_handle=False, refresh_cache=False):
        # forensics shell: the watchdog sees one armed unit of blocking
        # work; any escaping exception lands in the black box before it
        # propagates
        with _blackbox.guard("Executor.run"):
            return self._run_on_device_impl(
                program, feed, fetch_list, scope, device, return_numpy,
                as_handle=as_handle, refresh_cache=refresh_cache)

    def _run_on_device_impl(self, program, feed, fetch_list, scope, device,
                            return_numpy, as_handle=False,
                            refresh_cache=False):
        # flight-recorder guards: one module-bool load each; both False
        # leaves the hot path identical to the uninstrumented executor
        telem = _telemetry.ENABLED
        prof = _profiler.enabled()
        sp = (_stepprof.begin("async" if as_handle else "single")
              if _stepprof.ENABLED else None)
        t0 = time.perf_counter() if (telem or prof) else 0.0
        if sp is not None:
            sp.enter("feed")
        feeds, feed_specs = self._prepare_feeds(program, feed, device)
        if sp is not None:
            sp.exit()
        t_feed = time.perf_counter() if telem else 0.0
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        if sp is not None:
            # a cache hit closes this bracket in microseconds; a fresh
            # XLA trace shows up as a fat compile phase instead of
            # silently inflating the step
            sp.enter("compile")
        cp = self._get_compiled(program, feed_specs, fetch_names, scope,
                                refresh=refresh_cache)
        if sp is not None:
            sp.exit()
            # state gather + step-key derivation assemble the dispatch
            # inputs just like the feed dict does — same bracket, or
            # they'd surface as unattributed host time
            sp.enter("feed")
        state = self._gather_state(cp.state_in, scope, device)
        key = self._step_key(program)
        if sp is not None:
            sp.exit()
            # the bracket opens here, not at _dispatch: pre-dispatch
            # work — the profiler's own one-shot cost snapshot, the
            # blackbox record, the nan snapshot — is host dispatch
            # overhead and must be charged, not hidden in the
            # unattributed residual
            sp.enter("dispatch")
            sp.pre_dispatch(cp, state, feeds, key, program)
        # per-EXECUTABLE key: two feed shapes of one program do different
        # FLOPs, so the program fingerprint alone would mis-price steps
        fingerprint = (_telemetry.executable_fingerprint(cp, program)
                       if telem else None)
        flops_avals = (_telemetry.capture_step_avals(cp, state, feeds, key)
                       if telem else None)
        mem_dev = _telemetry.device_label(device) if telem else None
        if telem:
            # HBM ledger: feeds enter the device here; the predicted
            # plan is filed once per executable so the step records and
            # any OOM dump carry predicted-vs-measured peak
            _memory.track_feeds(feeds, mem_dev)
            _memory.register_plan_for(cp, program, feed_specs, fingerprint)
        if _blackbox.ENABLED:
            # the event a crash dump's last entry points at: what was
            # about to run, with the shapes that ran it
            _blackbox.record_dispatch(
                "Executor.run_async" if as_handle else "Executor.run",
                feed_specs=feed_specs, fetch_names=fetch_names,
                fingerprint=getattr(cp, "_exec_cache_key", None))
        nan_snapshot = self._nan_snapshot(cp, state)
        new_state, fetches = self._dispatch(cp, state, feeds, key,
                                            origin="Executor.dispatch")
        if sp is not None:
            sp.exit()
            # scope writeback is output handling on the host clock —
            # fetch-side work, even when the caller fetched nothing
            sp.enter("fetch")
        for n, val in new_state.items():
            scope.set_value(n, val)
        if telem:
            # scope binding: the step's outputs replace the donated
            # inputs under the same ledger keys; feeds leave with the
            # host references, fetched activations stay live until
            # materialized (below / FetchHandle.result)
            _memory.track_state(cp, program, new_state, mem_dev)
            _memory.track_fetches(cp.fetch_names, fetches, mem_dev)
            _memory.drop_feeds(feeds, mem_dev)
        if sp is not None:
            # the fetch bracket closes AFTER the ledger writeback: when
            # telemetry is co-enabled its per-step accounting is still
            # output handling on the host clock, not unattributed
            # residual
            sp.exit()
        if as_handle:
            # dispatch complete, nothing synced: the (optional) nan/inf
            # reductions are already in flight on device, but reading
            # their verdict waits for .result()
            raw_check = self._nan_check_start(
                new_state, cp.fetch_names, fetches)
            if raw_check is not None and nan_snapshot is not None:
                def nan_check(_raw=raw_check):
                    try:
                        _raw()
                    except RuntimeError as e:
                        Executor._nan_blame(e, program, nan_snapshot,
                                            feeds, key, device)
            else:
                nan_check = raw_check
            handle = FetchHandle(
                fetches, cp.fetch_names,
                nan_check=nan_check,
                track=_profiler.async_fetch_begin(cp.fetch_names)
                if prof else None,
                t_dispatch=t0 if telem else None,
                mem_device=mem_dev,
            )
            if sp is not None:
                # the span measured host dispatch latency only; device
                # + fetch happen in FetchHandle.result on the caller's
                # clock, so the record is marked dispatch_only
                _stepprof.finish(sp, feeds=feeds, dispatch_only=True)
            if telem or prof:
                t1 = time.perf_counter()
                if telem:
                    # dispatch_only: this wall is host dispatch latency,
                    # not step duration — kept out of percentiles/MFU
                    _telemetry.record_step(
                        "async", t1 - t0,
                        feed_bytes=sum(
                            getattr(a, "nbytes", 0)
                            for a in feeds.values()),
                        h2d_seconds=t_feed - t0, fingerprint=fingerprint,
                        dispatch_only=True)
                    if flops_avals is not None:
                        _telemetry.register_flops_from_avals(
                            cp, fingerprint, flops_avals)
                if prof:
                    _profiler.record_span("executor.dispatch", t0, t1)
            return handle
        try:
            self._check_nan_inf(new_state, cp.fetch_names, fetches)
        except RuntimeError as e:
            self._nan_blame(e, program, nan_snapshot, feeds, key, device)
        if return_numpy:
            if sp is not None:
                # device bracket: wait for compute to complete BEFORE
                # the host copy, so device time and d2h materialize are
                # attributed separately (annotated into the device
                # timeline when a jax.profiler trace session is live)
                sp.enter("device")
                with _stepprof.device_annotation():
                    for _f in fetches:
                        if hasattr(_f, "block_until_ready"):
                            _f.block_until_ready()
                sp.exit()
                sp.enter("fetch")
            fetches = _materialize_fetches(fetches, "Executor.run")
            if sp is not None:
                sp.exit()
        if sp is not None:
            # the span closes BEFORE telemetry's own record-keeping
            # tail: the observatory reports the same step wall whether
            # or not other observers are armed, and their bookkeeping
            # cannot masquerade as unattributed step residual
            _stepprof.finish(sp, feeds=feeds, fetches=fetches)
        if telem:
            # sync return: the fetch buffers are the caller's now (numpy
            # in hand, or live arrays the executor no longer owns)
            _memory.drop_fetches(cp.fetch_names, mem_dev)
        if telem or prof:
            t1 = time.perf_counter()
            if telem:
                _telemetry.record_step(
                    "single", t1 - t0,
                    feed_bytes=sum(
                        getattr(a, "nbytes", 0) for a in feeds.values()),
                    fetch_bytes=sum(
                        getattr(f, "nbytes", 0) for f in fetches),
                    h2d_seconds=t_feed - t0, fingerprint=fingerprint)
                if flops_avals is not None:
                    _telemetry.register_flops_from_avals(
                        cp, fingerprint, flops_avals)
            if prof:
                _profiler.record_span("executor.run", t0, t1)
        return fetches

    def run_async(self, program=None, feed=None, fetch_list=None,
                  feed_var_name="feed", fetch_var_name="fetch", scope=None):
        """``run`` without the host sync: dispatches one step and returns
        a :class:`FetchHandle` of live device arrays immediately — the
        XLA execution proceeds asynchronously and ``.result()``
        materializes numpy lazily, matching ``run(...)`` bit-for-bit.
        Scope state is updated with live (also non-blocking) arrays, so
        back-to-back dispatches chain on device without host round trips.
        """
        program = program or framework.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        device = self.place.jax_device()
        with jax.default_device(device):
            return self._run_on_device(
                program, feed, fetch_list, scope, device,
                return_numpy=False, as_handle=True,
            )

    def run_multi_step(self, program, steps, feed=None, fetch_list=None,
                       scope=None, return_numpy=True, stack_fetches=False):
        """Run ``steps`` iterations of ``program`` inside ONE compiled
        executable (lax.scan over the step function) — one host dispatch
        per K steps instead of per step. ``feed`` is constant across the
        steps (real pipelines use in-graph reader ops and need none).
        Fetches are the LAST step's values; pass stack_fetches=True for
        the per-step trajectory stacked along a leading [steps] axis
        (costs scan output buffers every iteration)."""
        from paddle_tpu.core.lowering import MultiStepProgram

        program = program or framework.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        device = self.place.jax_device()
        with jax.default_device(device):
            sp = (_stepprof.begin("multi_step")
                  if _stepprof.ENABLED else None)
            if sp is not None:
                sp.enter("feed")
            feeds, feed_specs = self._prepare_feeds(program, feed, device)
            if sp is not None:
                sp.exit()
                # cache-key derivation (fingerprint, scope signature) is
                # executable resolution — compile-phase work, exactly as
                # in the single-step path where _get_compiled owns it
                sp.enter("compile")
            fetch_names = [
                v.name if isinstance(v, framework.Variable) else str(v)
                for v in fetch_list
            ]
            if getattr(program, "_deferred_infer", None):
                program.infer_deferred_shapes(
                    feed_shapes={n: s
                                 for n, (s, _d) in feed_specs.items()})
            scope_names = self._scope_names(scope)
            key_id = (
                "multi", program_fingerprint(program), int(steps),
                tuple(sorted(feed_specs.items())), tuple(fetch_names),
                frozenset(scope_names), program._is_test,
                getattr(program, "_amp_dtype", None), bool(stack_fetches),
                trace_flags_key(), (device.platform, device.id),
            )
            cp = self._cache.get(key_id)
            if cp is None:
                exec_cache.record_trace_miss()
                exec_cache.configure()
                _maybe_verify(program, feed_specs, fetch_names,
                              origin="Executor.run_multi_step")
                _explain.record_compile({
                    "program": key_id[1],
                    "feed_specs": tuple(sorted(
                        (n, (s, d)) for n, (s, d) in feed_specs.items())),
                    "fetch_names": tuple(fetch_names),
                    "scope_signature": frozenset(scope_names),
                    "flags": trace_flags_key(),
                    "device": "%s:%d" % (device.platform, device.id),
                    "mode": "multi_step[%d]" % int(steps),
                })
                def _build():
                    if _chaos.ENABLED:
                        _chaos.fault("exec.compile")
                    return MultiStepProgram(
                        program, steps, feed_specs, fetch_names,
                        scope_names, is_test=program._is_test,
                        device=device, stack_fetches=stack_fetches,
                    )

                cp = _retry.call(_build, origin="Executor.compile")
                cp._exec_cache_key = executable_key(
                    program, feed_specs, fetch_names, scope_names,
                    extra=("multi", int(steps), bool(stack_fetches),
                           device.platform, device.id,
                           getattr(device, "device_kind", "")),
                )
                self._cache[key_id] = cp
            else:
                exec_cache.record_trace_hit()
            if sp is not None:
                sp.exit()
                # input assembly continues on the host clock: state
                # gather + step-key derivation feed the dispatch
                sp.enter("feed")
            state = self._gather_state(cp.state_in, scope, device)
            key = self._step_key(program)
            if sp is not None:
                sp.exit()
                # opens before the pre-dispatch work (cost snapshot,
                # blackbox record, nan snapshot, watchdog guard): host
                # dispatch overhead is charged to dispatch, not left in
                # the unattributed residual
                sp.enter("dispatch")
                sp.pre_dispatch(cp, state, feeds, key, program)
            telem = _telemetry.ENABLED
            prof = _profiler.enabled()
            t0 = time.perf_counter() if (telem or prof) else 0.0
            fingerprint = (_telemetry.executable_fingerprint(cp, program)
                           if telem else None)
            flops_avals = (_telemetry.capture_step_avals(
                cp, state, feeds, key) if telem else None)
            mem_dev = _telemetry.device_label(device) if telem else None
            if telem:
                _memory.track_feeds(feeds, mem_dev)
                _memory.register_plan_for(cp, program, feed_specs,
                                          fingerprint)
            if _blackbox.ENABLED:
                _blackbox.record_dispatch(
                    "Executor.run_multi_step", feed_specs=feed_specs,
                    fetch_names=fetch_names, steps=int(steps),
                    fingerprint=getattr(cp, "_exec_cache_key", None))
            nan_snapshot = self._nan_snapshot(cp, state)
            # scale: one dispatch legitimately blocks ~K× the per-step
            # p95 the watchdog's auto timeout is derived from
            with _blackbox.guard("Executor.run_multi_step",
                                 scale=int(steps)):
                new_state, fetches = self._dispatch(
                    cp, state, feeds, key,
                    origin="Executor.run_multi_step")
                if sp is not None:
                    sp.exit()
                    sp.enter("fetch")
                for n, val in new_state.items():
                    scope.set_value(n, val)
                if telem:
                    _memory.track_state(cp, program, new_state, mem_dev)
                    _memory.track_fetches(cp.fetch_names, fetches,
                                          mem_dev)
                    _memory.drop_feeds(feeds, mem_dev)
                if sp is not None:
                    # ledger writeback is fetch-side work (see run())
                    sp.exit()
                try:
                    self._check_nan_inf(new_state, cp.fetch_names, fetches)
                except RuntimeError as e:
                    self._nan_blame(e, program, nan_snapshot, feeds, key,
                                    device, steps=int(steps),
                                    mutable_state=cp.mutable_state,
                                    multi=True)
                if return_numpy:
                    if sp is not None:
                        sp.enter("device")
                        with _stepprof.device_annotation():
                            for _f in fetches:
                                if hasattr(_f, "block_until_ready"):
                                    _f.block_until_ready()
                        sp.exit()
                        sp.enter("fetch")
                    fetches = _materialize_fetches(
                        fetches, "Executor.run_multi_step")
                    if sp is not None:
                        sp.exit()
                if telem:
                    _memory.drop_fetches(cp.fetch_names, mem_dev)
            if sp is not None:
                # span closes before telemetry's record-keeping tail
                # (see run()): per-step wall is comparable across
                # observer configurations
                _stepprof.finish(sp, steps=int(steps), feeds=feeds,
                                 fetches=fetches)
            if telem or prof:
                t1 = time.perf_counter()
                if telem:
                    _telemetry.record_step(
                        "multi_step", t1 - t0, steps=int(steps),
                        feed_bytes=sum(
                            getattr(a, "nbytes", 0)
                            for a in feeds.values()),
                        fetch_bytes=sum(
                            getattr(f, "nbytes", 0) for f in fetches),
                        fingerprint=fingerprint)
                    if flops_avals is not None:
                        _telemetry.register_flops_from_avals(
                            cp, fingerprint, flops_avals,
                            steps=int(steps))
                if prof:
                    _profiler.record_span(
                        "executor.run_multi_step[%d]" % int(steps), t0, t1)
            return fetches

    def close(self):
        self._cache.clear()

    # -- parity helpers -----------------------------------------------------
    def _run_startup(self, startup_program=None, scope=None):
        self.run(
            startup_program or framework.default_startup_program(),
            feed={},
            fetch_list=[],
            scope=scope,
        )
