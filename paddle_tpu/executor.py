"""Executor: run Programs on a Place — by whole-program XLA compilation.

Reference parity: python/paddle/fluid/executor.py:374 (Executor.run feeds
numpy -> tensors, fetches back) + paddle/fluid/framework/executor.cc:163.
The TPU-first difference: instead of a sequential per-op interpreter loop
(executor.cc:392-404), ``run`` traces block 0 through the op lowerings into
one JAX function, jit-compiles it per (program version, feed shapes, fetch
set) — cached like the reference's ``use_program_cache`` — and executes a
single fused XLA program per step. Persistable vars (params, optimizer
state, BN stats) live in the Scope as device arrays and are threaded
through the step function with buffer donation (in-place semantics without
mutation).
"""

import numpy as np

import jax

from paddle_tpu import framework
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.lowering import CompiledProgram
from paddle_tpu.core.scope import Scope
from paddle_tpu.core.types import Place, TPUPlace, np_dtype

_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    """The scope Executor.run defaults to. Like the reference's
    ``fluid.global_scope()`` / ``scope_guard`` pair (executor.py:g_scope),
    ``scope_guard`` swaps what this returns for the duration of the guard."""
    return _scope_stack[-1]


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        _scope_stack.append(scope)
        try:
            yield
        finally:
            _scope_stack.pop()

    return guard()


def _as_feed_array(value, place):
    """numpy / LoDTensor / device array -> (array, lod or None). Device
    arrays (a double-buffered PyReader's prefetched feeds) pass through
    untouched — np.asarray would block on the in-flight transfer and
    round-trip the data through the host."""
    if isinstance(value, LoDTensor):
        return np.asarray(value.numpy()), value.lod() or None
    if isinstance(value, jax.Array):
        return value, None
    return np.asarray(value), None


# Flags whose value changes what the block lowers TO (not just runtime
# behavior); they join the executable cache key so toggling recompiles.
_TRACE_FLAGS = ("use_pallas_lstm", "use_pallas_gru", "remat_gradients",
                "conv_nhwc", "attention_impl")


def _trace_flags_key():
    from paddle_tpu import flags

    return tuple((n, flags.get(n)) for n in _TRACE_FLAGS)


class Executor(object):
    def __init__(self, place=None):
        self.place = place if place is not None else TPUPlace()
        if not isinstance(self.place, Place):
            raise TypeError("place must be a Place (TPUPlace()/CPUPlace())")
        self._cache = {}
        self._run_counter = 0
        self._base_seed = np.random.randint(0, 2**31 - 1)

    # -- compilation cache --------------------------------------------------
    def _get_compiled(self, program, feed_specs, fetch_names, scope):
        scope_names = self._scope_names(scope)
        key = (
            id(program),
            program._version,
            tuple(sorted((n, s, d) for n, (s, d) in feed_specs.items())),
            tuple(fetch_names),
            id(scope),
            # Scope contents shape the step signature (state_in): a var
            # initialized later (e.g. startup program ran) must recompile.
            hash(frozenset(scope_names)),
            program._is_test,
            getattr(program, "_amp_dtype", None),
            # trace-time flags alter the lowered computation; toggling one
            # must recompile, not reuse the stale executable
            _trace_flags_key(),
        )
        cp = self._cache.get(key)
        if cp is None:
            cp = CompiledProgram(
                program,
                feed_specs,
                fetch_names,
                scope_names,
                is_test=program._is_test,
                device=self.place.jax_device(),
            )
            self._cache[key] = cp
        return cp

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        program = program or framework.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        device = self.place.jax_device()
        if not use_program_cache:
            # reference use_program_cache=False semantics: drop this
            # program's cached single-run executables so the next run
            # retraces (multi-step scan executables are keyed separately
            # and survive — they are expensive compiles run() never uses)
            self._cache = {
                k: v for k, v in self._cache.items()
                if k[0] == "multi" or k[0] != id(program)
            }
        # Everything below (feed transfer, key creation, dispatch) stays on
        # the Place's device: with several backends loaded (TPU plugin +
        # CPU), stray ops like PRNGKey would otherwise run on the default
        # platform — wrong device, and unsafe under concurrent serving.
        with jax.default_device(device):
            return self._run_on_device(
                program, feed, fetch_list, scope, device, return_numpy
            )

    # -- shared run plumbing -------------------------------------------------
    def _prepare_feeds(self, program, feed, device):
        """numpy/LoDTensor feeds -> (device arrays, (shape, dtype) specs),
        cast to the declared var dtype when compatible."""
        feeds = {}
        feed_specs = {}
        for name, value in feed.items():
            arr, _lod = _as_feed_array(value, self.place)
            var = program.global_block()._find_var_recursive(name)
            if (var is not None and var.dtype
                    and arr.dtype != np_dtype(var.dtype)):
                if np.issubdtype(arr.dtype, np.floating) or np.issubdtype(
                    arr.dtype, np.integer
                ):
                    arr = arr.astype(np_dtype(var.dtype))
            feeds[name] = jax.device_put(arr, device)
            feed_specs[name] = (tuple(arr.shape), str(arr.dtype))
        return feeds, feed_specs

    @staticmethod
    def _scope_names(scope):
        names = set()
        s = scope
        while s is not None:
            names.update(s.local_var_names())
            s = s._parent
        return names

    @staticmethod
    def _gather_state(state_in, scope, device):
        state = {}
        for n in state_in:
            v = scope.find_var(n)
            if v is None or v.value is None:
                raise RuntimeError(
                    "persistable variable %r is not initialized in the scope "
                    "(did you run the startup program?)" % n
                )
            val = v.value
            if not isinstance(val, jax.Array):
                val = jax.device_put(np.asarray(val), device)
            elif val.sharding.device_set != {device}:
                # Scope value lives on another Place's device (e.g. trained
                # on TPU, now serving on CPU): move it once.
                val = jax.device_put(val, device)
            state[n] = val
        return state

    def _step_key(self, program):
        self._run_counter += 1
        return jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed or self._base_seed),
            self._run_counter,
        )

    @staticmethod
    def _check_nan_inf(new_state, fetch_names, fetches):
        from paddle_tpu import flags as _flags

        if not _flags.get("check_nan_inf"):
            return
        # FLAGS_check_nan_inf (operator.cc:754): scan every produced
        # value host-side and fail loudly on the first bad one.
        for name, val in list(new_state.items()) + list(
            zip(fetch_names, fetches)
        ):
            arr = np.asarray(val)
            if np.issubdtype(arr.dtype, np.floating) and not np.all(
                np.isfinite(arr)
            ):
                raise RuntimeError(
                    "NaN/Inf detected in variable %r after program run "
                    "(FLAGS_check_nan_inf)" % name
                )

    def _run_on_device(self, program, feed, fetch_list, scope, device,
                       return_numpy):
        feeds, feed_specs = self._prepare_feeds(program, feed, device)
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        cp = self._get_compiled(program, feed_specs, fetch_names, scope)
        state = self._gather_state(cp.state_in, scope, device)
        key = self._step_key(program)
        new_state, fetches = cp(state, feeds, key)
        for n, val in new_state.items():
            scope.set_value(n, val)
        self._check_nan_inf(new_state, cp.fetch_names, fetches)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    def run_multi_step(self, program, steps, feed=None, fetch_list=None,
                       scope=None, return_numpy=True, stack_fetches=False):
        """Run ``steps`` iterations of ``program`` inside ONE compiled
        executable (lax.scan over the step function) — one host dispatch
        per K steps instead of per step. ``feed`` is constant across the
        steps (real pipelines use in-graph reader ops and need none).
        Fetches are the LAST step's values; pass stack_fetches=True for
        the per-step trajectory stacked along a leading [steps] axis
        (costs scan output buffers every iteration)."""
        from paddle_tpu.core.lowering import MultiStepProgram

        program = program or framework.default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        device = self.place.jax_device()
        with jax.default_device(device):
            feeds, feed_specs = self._prepare_feeds(program, feed, device)
            fetch_names = [
                v.name if isinstance(v, framework.Variable) else str(v)
                for v in fetch_list
            ]
            scope_names = self._scope_names(scope)
            key_id = (
                "multi", id(program), program._version, int(steps),
                tuple(sorted(feed_specs.items())), tuple(fetch_names),
                id(scope), hash(frozenset(scope_names)), program._is_test,
                getattr(program, "_amp_dtype", None), bool(stack_fetches),
            )
            cp = self._cache.get(key_id)
            if cp is None:
                cp = MultiStepProgram(
                    program, steps, feed_specs, fetch_names, scope_names,
                    is_test=program._is_test, device=device,
                    stack_fetches=stack_fetches,
                )
                self._cache[key_id] = cp
            state = self._gather_state(cp.state_in, scope, device)
            key = self._step_key(program)
            new_state, fetches = cp(state, feeds, key)
            for n, val in new_state.items():
                scope.set_value(n, val)
            self._check_nan_inf(new_state, cp.fetch_names, fetches)
            if return_numpy:
                fetches = [np.asarray(f) for f in fetches]
            return fetches

    def close(self):
        self._cache.clear()

    # -- parity helpers -----------------------------------------------------
    def _run_startup(self, startup_program=None, scope=None):
        self.run(
            startup_program or framework.default_startup_program(),
            feed={},
            fetch_list=[],
            scope=scope,
        )
