"""Weighted averaging across fetched batch values
(python/paddle/fluid/average.py parity)."""

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage(object):
    """Accumulate (value, weight) pairs; eval() = weighted mean. The
    typical use is averaging per-batch losses weighted by batch size."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        """Accumulate a scalar or array value (upstream accepts matrices
        and averages element-wise)."""
        value = np.asarray(value, dtype=np.float64)
        w = float(weight)
        self.numerator = self.numerator + value * w
        self.denominator += w

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "WeightedAverage.eval() before any add() (zero weight)")
        out = self.numerator / self.denominator
        return float(out) if np.ndim(out) == 0 else out
