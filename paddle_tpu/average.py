"""Weighted averaging across fetched batch values
(python/paddle/fluid/average.py parity)."""

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage(object):
    """Accumulate (value, weight) pairs; eval() = weighted mean. The
    typical use is averaging per-batch losses weighted by batch size."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        value = np.ravel(np.asarray(value, dtype=np.float64))
        if value.size != 1:
            raise ValueError("add() expects a scalar value, got shape %s"
                             % (value.shape,))
        w = float(weight)
        self.numerator += float(value[0]) * w
        self.denominator += w

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "WeightedAverage.eval() before any add() (zero weight)")
        return self.numerator / self.denominator
