"""ctypes binding to the native host runtime (native/libptpu_core.so).

Reference parity: the pybind layer role (paddle/fluid/pybind/pybind.cc) for
the host-side native components — recordio file IO, the blocking batch
queue, the C++ Scope, and the PTPB program IR parser. pybind11 is not in
the image, so the binding is a plain C API + ctypes (SURVEY.md §2.9 item
11). The library builds on demand with cmake+ninja (or a direct g++
fallback) and is cached under native/build/.

Usage:
    from paddle_tpu import native
    if native.available():
        q = native.NativeBlockingQueue(capacity=8)
        w = native.RecordIOWriter(path)
"""

import ctypes
import os
import subprocess
import threading

from paddle_tpu.observability import lock_witness

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libptpu_core.so")

_lib = None
_lib_lock = lock_witness.make_lock("native.lib")
_build_error = None


def _stale():
    """True when any native source is newer than the built library —
    the cmake path rebuilds incrementally anyway, but the bare-g++
    fallback (and a pre-built .so from an older checkout) would
    otherwise serve stale code silently."""
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    for sub in ("src", "include"):
        root = os.path.join(_NATIVE_DIR, sub)
        for dirpath, _, files in os.walk(root):
            for fn in files:
                try:
                    if os.path.getmtime(os.path.join(dirpath, fn)) \
                            > lib_mtime:
                        return True
                except OSError:
                    continue
    return False


def _build_library():
    """Compile libptpu_core.so (cmake+ninja, falling back to bare g++)."""
    build_dir = os.path.join(_NATIVE_DIR, "build")
    try:
        subprocess.run(
            ["cmake", "-S", _NATIVE_DIR, "-B", build_dir, "-G", "Ninja"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["cmake", "--build", build_dir], check=True, capture_output=True
        )
        return
    except (OSError, subprocess.CalledProcessError):
        pass
    os.makedirs(build_dir, exist_ok=True)
    subprocess.run(
        [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
            "-I", os.path.join(_NATIVE_DIR, "include"),
            "-I", os.path.join(_NATIVE_DIR, "src"),
            os.path.join(_NATIVE_DIR, "src", "c_api.cc"),
            "-o", _LIB_PATH,
        ],
        check=True, capture_output=True,
    )


def _declare(lib):
    c = ctypes
    P = c.c_void_p
    sigs = {
        "ptpu_last_error": ([], c.c_char_p),
        "ptpu_recordio_writer_open": ([c.c_char_p], P),
        "ptpu_recordio_write": ([P, c.c_void_p, c.c_uint64], c.c_int),
        "ptpu_recordio_writer_close": ([P], c.c_int),
        "ptpu_recordio_reader_open": ([c.c_char_p], P),
        "ptpu_recordio_next": ([P], c.c_int64),
        "ptpu_recordio_read": ([P, c.c_void_p, c.c_uint64], c.c_int),
        "ptpu_recordio_reader_close": ([P], c.c_int),
        "ptpu_queue_create": ([c.c_uint64], P),
        "ptpu_queue_push": ([P, c.c_void_p, c.c_uint64, c.c_int64], c.c_int),
        "ptpu_queue_pop": ([P, c.c_void_p, c.c_uint64, c.c_int64], c.c_int64),
        "ptpu_queue_size": ([P], c.c_uint64),
        "ptpu_queue_capacity": ([P], c.c_uint64),
        "ptpu_queue_close": ([P], None),
        "ptpu_queue_kill": ([P], None),
        "ptpu_queue_is_closed": ([P], c.c_int),
        "ptpu_queue_reopen": ([P], None),
        "ptpu_queue_destroy": ([P], None),
        "ptpu_scope_create": ([], P),
        "ptpu_scope_new_child": ([P], P),
        "ptpu_scope_set": (
            [P, c.c_char_p, c.c_char_p, c.POINTER(c.c_int64), c.c_int32,
             c.c_void_p, c.c_uint64], c.c_int),
        "ptpu_scope_get_meta": (
            [P, c.c_char_p, c.c_char_p, c.c_uint64, c.POINTER(c.c_int64),
             c.POINTER(c.c_int32)], c.c_int64),
        "ptpu_scope_get_data": ([P, c.c_char_p, c.c_void_p, c.c_uint64],
                                c.c_int),
        "ptpu_scope_erase": ([P, c.c_char_p], c.c_int),
        "ptpu_scope_num_vars": ([P], c.c_uint64),
        "ptpu_scope_list": ([P, c.c_char_p, c.c_uint64], c.c_int64),
        "ptpu_scope_destroy": ([P], None),
        "ptpu_program_parse": ([c.c_void_p, c.c_uint64], P),
        "ptpu_program_num_blocks": ([P], c.c_int32),
        "ptpu_program_num_ops": ([P, c.c_int32], c.c_int32),
        "ptpu_program_num_vars": ([P, c.c_int32], c.c_int32),
        "ptpu_program_op_type": ([P, c.c_int32, c.c_int32, c.c_char_p,
                                  c.c_uint64], c.c_int64),
        "ptpu_program_serialize": ([P, c.c_void_p, c.c_uint64], c.c_int64),
        "ptpu_program_destroy": ([P], None),
        "ptpu_interp_run": ([P, P, c.c_int32], c.c_int),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype


def get_lib():
    """Load (building if needed) the native library; None if unbuildable.

    A failed stale-rebuild falls back to loading the existing library:
    stale-but-working beats none (e.g. a shipped prebuilt .so on a
    machine with no toolchain whose file mtimes got scrambled by the
    copy)."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        try:
            if not os.path.exists(_LIB_PATH):
                _build_library()
            elif _stale():
                try:
                    _build_library()
                except Exception:
                    pass  # keep serving the existing (stale) library
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except Exception as e:  # missing toolchain, RO filesystem, ...
            _build_error = e
            return None
        return _lib


def available():
    """True if the library is loadable, BUILDING it on first call if the
    toolchain is present (explicit opt-in path: tests, setup scripts)."""
    return get_lib() is not None


def prebuilt():
    """True only if libptpu_core.so is already built AND fresh — never
    triggers a compile. Hot paths (PyReader) use this so constructing a
    reader never stalls on a surprise cmake build. A STALE prebuilt lib
    returns False instead of being loaded: loading it would cache the
    stale handle into _lib and silently bypass the rebuild every later
    get_lib() would otherwise run (CDLL handles can't be reloaded
    in-process)."""
    if _lib is not None:
        return True
    if not os.path.exists(_LIB_PATH) or _stale():
        return False
    return get_lib() is not None  # fresh: no build can trigger


def last_error():
    lib = get_lib()
    return lib.ptpu_last_error().decode() if lib else str(_build_error)


class RecordIOWriter(object):
    """CRC32-framed record file writer (recordio capability)."""

    def __init__(self, path):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable: %s"
                               % _build_error)
        self._h = self._lib.ptpu_recordio_writer_open(path.encode())
        if not self._h:
            raise IOError(last_error())

    def write(self, data):
        data = bytes(data)
        rc = self._lib.ptpu_recordio_write(self._h, data, len(data))
        if rc != 0:
            raise IOError(last_error())

    def close(self):
        if self._h:
            self._lib.ptpu_recordio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordIOReader(object):
    """Iterator over a recordio file; raises IOError on corrupt records."""

    def __init__(self, path):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable: %s"
                               % _build_error)
        self._h = self._lib.ptpu_recordio_reader_open(path.encode())
        if not self._h:
            raise IOError(last_error())

    def __iter__(self):
        return self

    def __next__(self):
        n = self._lib.ptpu_recordio_next(self._h)
        if n == -1:
            raise StopIteration
        if n < 0:
            raise IOError(last_error())
        buf = ctypes.create_string_buffer(n)
        if self._lib.ptpu_recordio_read(self._h, buf, n) != 0:
            raise IOError(last_error())
        return buf.raw

    def close(self):
        if self._h:
            self._lib.ptpu_recordio_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeBlockingQueue(object):
    """C++-backed bounded byte queue (LoDTensorBlockingQueue role). Items
    are bytes; reader/py_reader layers serialize batches with numpy."""

    def __init__(self, capacity):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable: %s"
                               % _build_error)
        self._h = self._lib.ptpu_queue_create(capacity)
        self.capacity = capacity

    def push(self, data, timeout_ms=-1):
        data = bytes(data)
        rc = self._lib.ptpu_queue_push(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise TimeoutError("queue push timed out")
        return rc == 0

    def pop(self, timeout_ms=-1):
        """bytes, or None when the queue is closed and drained."""
        while True:
            n = self._lib.ptpu_queue_pop(self._h, None, 0, timeout_ms)
            if n == -2:
                raise TimeoutError("queue pop timed out")
            if n == 0:
                return None
            buf = ctypes.create_string_buffer(n)
            n2 = self._lib.ptpu_queue_pop(self._h, buf, n, timeout_ms)
            if n2 == 0:
                return None
            if n2 == -3:
                continue  # another consumer raced us; re-peek the new head
            if n2 == -2:
                raise TimeoutError("queue pop timed out")
            return buf.raw[:n2]

    def size(self):
        return self._lib.ptpu_queue_size(self._h)

    def close(self):
        self._lib.ptpu_queue_close(self._h)

    def kill(self):
        """Close AND discard queued items (abort semantics)."""
        self._lib.ptpu_queue_kill(self._h)

    def is_closed(self):
        return bool(self._lib.ptpu_queue_is_closed(self._h))

    def reopen(self):
        self._lib.ptpu_queue_reopen(self._h)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.ptpu_queue_destroy(h)


class NativeScope(object):
    """C++ Scope holding named host ndarrays (Scope/Variable role)."""

    def __init__(self, _handle=None, _lib=None):
        self._lib = _lib or get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable: %s"
                               % _build_error)
        self._owned = _handle is None
        self._h = _handle or self._lib.ptpu_scope_create()

    def new_child(self):
        return NativeScope(
            _handle=self._lib.ptpu_scope_new_child(self._h), _lib=self._lib
        )

    def set(self, name, array):
        import numpy as np

        a = np.ascontiguousarray(array)
        dims = (ctypes.c_int64 * a.ndim)(*a.shape)
        rc = self._lib.ptpu_scope_set(
            self._h, name.encode(), str(a.dtype).encode(), dims, a.ndim,
            a.ctypes.data_as(ctypes.c_void_p), a.nbytes,
        )
        if rc != 0:
            raise RuntimeError(last_error())

    def get(self, name):
        """numpy array, or None if the var is absent (FindVar walk)."""
        import numpy as np

        dtype_buf = ctypes.create_string_buffer(32)
        dims = (ctypes.c_int64 * 16)()
        ndim = ctypes.c_int32()
        nbytes = self._lib.ptpu_scope_get_meta(
            self._h, name.encode(), dtype_buf, 32, dims, ctypes.byref(ndim)
        )
        if nbytes < 0:
            return None
        out = np.empty(
            tuple(dims[i] for i in range(ndim.value)),
            dtype=np.dtype(dtype_buf.value.decode()),
        )
        if nbytes:
            rc = self._lib.ptpu_scope_get_data(
                self._h, name.encode(),
                out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
            )
            if rc != 0:
                raise RuntimeError(last_error())
        return out

    def erase(self, name):
        return self._lib.ptpu_scope_erase(self._h, name.encode()) == 0

    def var_names(self):
        need = self._lib.ptpu_scope_list(self._h, None, 0)
        buf = ctypes.create_string_buffer(int(need))
        self._lib.ptpu_scope_list(self._h, buf, need)
        joined = buf.value.decode()
        return sorted(joined.split("\n")) if joined else []

    def __len__(self):
        return int(self._lib.ptpu_scope_num_vars(self._h))

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_owned", False):
            self._lib.ptpu_scope_destroy(h)


def parse_program_bytes(data):
    """Parse PTPB bytes in C++ and return (num_blocks, ops_per_block,
    reserialized_bytes) — used to lockstep-test against program_bin.py."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable: %s" % _build_error)
    data = bytes(data)
    h = lib.ptpu_program_parse(data, len(data))
    if not h:
        raise ValueError(last_error())
    try:
        nblocks = lib.ptpu_program_num_blocks(h)
        ops = [lib.ptpu_program_num_ops(h, b) for b in range(nblocks)]
        need = lib.ptpu_program_serialize(h, None, 0)
        buf = ctypes.create_string_buffer(int(need))
        lib.ptpu_program_serialize(h, buf, need)
        return nblocks, ops, buf.raw[:need]
    finally:
        lib.ptpu_program_destroy(h)
