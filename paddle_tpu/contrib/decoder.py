"""Customizable RNN decoder DSL: InitState / StateCell / TrainingDecoder /
BeamSearchDecoder.

Reference parity: ``python/paddle/fluid/contrib/decoder/beam_search_decoder.py``
(the high-level decoder API over StateCell). TPU-first differences:

- TrainingDecoder drives this framework's DynamicRNN (scan-based), so the
  user's state-updater callback builds ops inside the scanned step block
  exactly as in the reference; ``need_reorder`` is accepted and ignored
  (no LoD rank sorting exists in the dense-padded design — masks do that
  job, docs/LOD_DESIGN.md).
- BeamSearchDecoder keeps the reference's dense [batch, beam] lattice
  CONSTANT-shaped (beam_search_ops.py design): finished beams freeze at
  end_id instead of shrinking the candidate set, and the generation loop
  is laid out step-by-step at graph-build time (max_len static), which
  XLA compiles into one executable. Batch size must be static — the
  per-step parent backtracking gathers need it.
"""

import numpy as np

from paddle_tpu.param_attr import ParamAttr

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class InitState(object):
    """Initial hidden-state holder (reference InitState contract)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        from paddle_tpu.layers import tensor as tensor_layers

        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of InitState")
        else:
            self._init = tensor_layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._need_reorder = need_reorder  # accepted; masks replace LoD sort

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell(object):
    """Named hidden states + step inputs + a user-registered updater.

    Usage (reference-compatible)::

        cell = StateCell(inputs={'x': None}, states={'h': init_h},
                         out_state='h')

        @cell.state_updater
        def updater(cell):
            h = cell.get_state('h')
            x = cell.get_input('x')
            cell.set_state('h', layers.fc(input=[x, h], size=D, act='tanh'))
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._out_state = out_state
        self._state_updater = None
        self._decoder = None
        if out_state not in self._cur_states:
            raise ValueError("out_state must be one state in states")

    # -- decoder hand-off ---------------------------------------------------

    def _enter_decoder(self, decoder):
        if self._decoder is not None:
            raise ValueError("StateCell has already entered a decoder")
        self._decoder = decoder

    def _leave_decoder(self, decoder):
        if self._decoder is not decoder:
            raise ValueError("inconsistent decoder object in StateCell")
        self._decoder = None

    def _set_raw_state(self, state_name, value):
        self._cur_states[state_name] = value

    # -- user API -----------------------------------------------------------

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError("unknown state %r" % state_name)
        state = self._cur_states[state_name]
        return state.value if isinstance(state, InitState) else state

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError("invalid input %r" % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater
        return updater

    def compute_state(self, inputs):
        for name, value in inputs.items():
            if name not in self._inputs:
                raise ValueError(
                    "unknown input %r (declared: %s)"
                    % (name, sorted(self._inputs)))
            self._inputs[name] = value
        if self._state_updater is None:
            raise ValueError("no state_updater registered")
        self._state_updater(self)

    def update_states(self):
        if self._decoder is not None:
            self._decoder._update_states(self)

    def out_state(self):
        return self.get_state(self._out_state)


class TrainingDecoder(object):
    """Training-time RNN decoder over a StateCell (reference contract)::

        decoder = TrainingDecoder(state_cell)
        with decoder.block():
            w = decoder.step_input(trg_embedding)     # [B, T, D]
            decoder.state_cell.compute_state(inputs={'x': w})
            score = layers.fc(decoder.state_cell.get_state('h'),
                              size=V, act='softmax')
            decoder.state_cell.update_states()
            decoder.output(score)
        out = decoder()                               # [B, T, V]
    """

    def __init__(self, state_cell, name=None):
        from paddle_tpu.layers.control_flow import DynamicRNN

        self._rnn = DynamicRNN(name=name)
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._memories = {}  # state name -> rnn memory var

    @property
    def state_cell(self):
        return self._state_cell

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            try:
                with self._rnn.block():
                    # materialize every state as a scan memory
                    for name in self._state_cell._state_names:
                        init = self._state_cell._cur_states[name]
                        assert isinstance(init, InitState), (
                            "decoder.block() must be entered before the "
                            "cell computes states")
                        mem = self._rnn.memory(init=init.value)
                        self._memories[name] = mem
                        self._state_cell._set_raw_state(name, mem)
                    yield
            finally:
                # release the cell even if the user's block raised, so a
                # corrected decoder can be built from the same cell
                self._state_cell._leave_decoder(self)

        return guard()

    def step_input(self, x):
        return self._rnn.step_input(x)

    def static_input(self, x):
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._rnn.output(*outputs)

    def _update_states(self, cell):
        for name, mem in self._memories.items():
            new = cell._cur_states[name]
            if new is not mem:
                self._rnn.update_memory(mem, new)
                cell._set_raw_state(name, mem)

    def __call__(self):
        return self._rnn()


class BeamSearchDecoder(object):
    """Generation-time beam-search decoder over a StateCell.

    The reference builds a while-loop over LoD-shrinking candidate sets
    (beam_search_decoder.py:420+); here the loop is laid out at build
    time over the dense constant-shape [batch, beam] lattice that this
    framework's beam_search op works on, and the per-step state update
    is the SAME user updater the training decoder ran — so one StateCell
    definition serves both decoders, the reference's design goal.

    Args follow the reference: init_ids [B, 1] int64, init_scores [B, 1]
    float32, target vocabulary size, word embedding dim; the embedding
    parameter name is ``word_emb`` by default so generation can share the
    training embedding via ParamAttr naming.
    """

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=4, end_id=1,
                 name=None, emb_param_name="word_emb",
                 score_param_name="beam_score_fc"):
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._v = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = topk_size  # accepted; dense top-k uses beam*V
        self._sparse_emb = sparse_emb
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._emb_param_name = emb_param_name
        self._score_param_name = score_param_name
        self._decoded = None

    @property
    def state_cell(self):
        return self._state_cell

    def _update_states(self, cell):
        pass  # beam states update positionally inside decode()

    def decode(self):
        """Build the unrolled generation graph. Returns
        (sentence_ids [B, beam, <=max_len], sentence_scores)."""
        from paddle_tpu import layers

        cell = self._state_cell
        # validate BEFORE mutating the cell, releasing it on failure so a
        # corrected decoder can be built from the same cell
        B = self._init_ids.shape[0] if self._init_ids.shape else None
        if B is None or int(B) < 0:
            cell._leave_decoder(self)
            raise ValueError(
                "BeamSearchDecoder needs a static batch size on init_ids "
                "(the per-step parent gathers index a [batch*beam] "
                "lattice); declare the input with append_batch_size=False "
                "or a fixed shape")
        B, K = int(B), self._beam_size

        input_names = [n for n in cell._inputs
                       if n not in self._input_var_dict]
        if len(input_names) != 1:
            cell._leave_decoder(self)
            raise ValueError(
                "StateCell must declare exactly one step input beyond "
                "input_var_dict (the previous-word embedding); got %s"
                % input_names)
        word_input = input_names[0]

        try:
            return self._build(cell, B, K, word_input)
        finally:
            # release the cell even when the user's updater raises mid
            # build, so a corrected decoder can reuse it
            if cell._decoder is self:
                cell._leave_decoder(self)

    def _build(self, cell, B, K, word_input):
        from paddle_tpu import layers

        # expand every state and static input to the beam lattice
        # [B, ...] -> [B*K, ...]
        def to_beam(v):
            e = layers.expand(layers.unsqueeze(v, axes=[1]),
                              expand_times=[1, K] + [1] * (len(v.shape) - 1))
            return layers.reshape(e, [B * K] + list(v.shape[1:]))

        for name in cell._state_names:
            init = cell._cur_states[name]
            val = init.value if isinstance(init, InitState) else init
            cell._set_raw_state(name, to_beam(val))
        beam_inputs = {n: to_beam(v)
                       for n, v in self._input_var_dict.items()}

        prev_ids = layers.reshape(self._init_ids, [B, 1])
        prev_ids = layers.expand(prev_ids, expand_times=[1, K])  # [B, K]
        # [0, -inf, ...] seed (identical initial beams must not produce
        # duplicate candidates) shifted by the caller's init_scores
        seed = np.full((1, K), -1e9, "float32")
        seed[0, 0] = 0.0
        prev_scores = layers.elementwise_add(
            layers.expand(layers.assign(seed), expand_times=[B, 1]),
            layers.expand(layers.reshape(self._init_scores, [B, 1]),
                          expand_times=[1, K]))

        offsets = layers.assign(
            (np.arange(B, dtype="int64")[:, None] * K).repeat(K, axis=1))

        step_ids, step_parents, step_scores = [], [], []
        for _ in range(self._max_len):
            emb = layers.embedding(
                layers.reshape(prev_ids, [B * K, 1]),
                size=[self._v, self._word_dim],
                is_sparse=self._sparse_emb,
                param_attr=ParamAttr(name=self._emb_param_name))
            cell.compute_state(inputs=dict(
                beam_inputs, **{word_input: emb}))
            out = cell.out_state()  # [B*K, H]
            logits = layers.fc(
                input=out, size=self._v,
                param_attr=ParamAttr(
                    name=self._score_param_name + ".w"),
                bias_attr=ParamAttr(
                    name=self._score_param_name + ".b"))
            # stable log-softmax: shifted - log(sum(exp(shifted))).
            # log-after-softmax underflows to -inf for tokens far below
            # the max, poisoning the accumulated totals.
            shifted = layers.elementwise_sub(
                logits, layers.reduce_max(logits, dim=-1, keep_dim=True))
            log_probs = layers.elementwise_sub(
                shifted,
                layers.log(layers.reduce_sum(
                    layers.exp(shifted), dim=-1, keep_dim=True)))
            # accumulate: candidate total = beam total + step log-prob
            # (beam_search with is_accumulated=True expects TOTALS; the
            # op only uses pre_scores to freeze finished beams)
            totals = layers.elementwise_add(
                layers.reshape(log_probs, [B, K, self._v]),
                layers.unsqueeze(prev_scores, axes=[2]))
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids=prev_ids, pre_scores=prev_scores, scores=totals,
                beam_size=K, end_id=self._end_id)
            # reorder every state by the parent beam
            flat_parent = layers.reshape(
                layers.elementwise_add(parent, offsets), [B * K])
            for name in cell._state_names:
                cell._set_raw_state(
                    name, layers.gather(cell._cur_states[name], flat_parent))
            step_ids.append(sel_ids)
            step_parents.append(parent)
            step_scores.append(sel_scores)
            prev_ids, prev_scores = sel_ids, sel_scores

        ids_t = layers.stack(step_ids, axis=0)        # [T, B, K]
        parents_t = layers.stack(step_parents, axis=0)
        scores_t = layers.stack(step_scores, axis=0)
        self._decoded = layers.beam_search_decode(
            ids=ids_t, parent_idx=parents_t, scores=scores_t,
            beam_size=K, end_id=self._end_id)
        return self._decoded  # decode()'s finally releases the cell

    def __call__(self):
        if self._decoded is None:
            return self.decode()
        return self._decoded
