"""Contrib utilities (python/paddle/fluid/contrib parity).

memory_usage   - estimate a Program's device-memory band for a batch size
                 (contrib/memory_usage_calc.py role).
op_freq_statis - unigram + adjacent-pair op frequency statistics
                 (contrib/op_frequence.py role).
QuantizeTranspiler is re-exported from transpiler (the contrib/quantize
package's home in the reference); the contrib beam-search decoder's
capability lives in ops/beam_search_ops.py + layers (COVERAGE.md).
"""

from collections import OrderedDict

from paddle_tpu.transpiler.quantize_transpiler import (  # noqa: F401
    QuantizeTranspiler,
)

__all__ = ["memory_usage", "op_freq_statistic", "op_freq_statis",
           "QuantizeTranspiler", "InitState", "StateCell",
           "TrainingDecoder", "BeamSearchDecoder"]

_DTYPE_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int16": 2, "int32": 4, "int64": 8, "bool": 1, "uint8": 1, "int8": 1,
}

# The reference reports a 70%-100% band of the summed var sizes (memory
# reuse makes the true footprint land inside it); same convention here.
_LOWER_FRACTION = 0.7


def memory_usage(program, batch_size):
    """Estimate `program`'s tensor memory for `batch_size` rows.

    Returns (lower, upper, unit): the estimated band, scaled to the
    largest of B/KB/MB/GB. -1 leading dims are replaced by batch_size.
    Under XLA the true footprint is the compiled executable's (buffer
    reuse + donation below this bound); this is the graph-level estimate
    the reference tooling exposes.
    """
    from paddle_tpu import framework

    if not isinstance(program, framework.Program):
        raise TypeError(
            "memory_usage expects a Program, got %s" % type(program))
    if int(batch_size) <= 0:
        raise ValueError("batch_size must be positive")

    total = 0.0
    for var in program.list_vars():
        shape = list(var.shape or ())
        if not shape:
            continue
        count = 1
        for d in shape:
            d = int(d)
            count *= batch_size if d < 0 else d
        total += count * _DTYPE_SIZE.get(str(var.dtype), 4)

    unit = "B"
    for next_unit in ("KB", "MB", "GB"):
        if total < 1024:
            break
        total /= 1024.0
        unit = next_unit
    return total * _LOWER_FRACTION, total, unit


def op_freq_statis(program):
    """Op frequency statistics: (unigram, adjacent-pair) OrderedDicts,
    most frequent first. Pairs are "producer->consumer" op types chained
    through non-parameter vars — the hot-path fusion-candidate report of
    the reference tool."""
    from paddle_tpu import framework

    if not isinstance(program, framework.Program):
        raise TypeError(
            "op_freq_statis expects a Program, got %s" % type(program))

    params = {p.name for p in program.global_block().all_parameters()}
    uni = {}
    var_producer = {}
    pair = {}
    for op in program.global_block().ops:
        uni[op.type] = uni.get(op.type, 0) + 1
        for name in op.input_arg_names():
            prev = var_producer.get(name)
            if prev is not None and name not in params:
                key = "%s->%s" % (prev, op.type)
                pair[key] = pair.get(key, 0) + 1
        for name in op.output_arg_names():
            if name not in params:
                var_producer[name] = op.type
    order = lambda d: OrderedDict(
        sorted(d.items(), key=lambda kv: -kv[1]))
    return order(uni), order(pair)


from paddle_tpu.contrib.decoder import (  # noqa: E402,F401
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)

# reference name (contrib/op_frequence.py:op_freq_statistic); the
# shorter alias predates the rename and is kept for compatibility
op_freq_statistic = op_freq_statis
