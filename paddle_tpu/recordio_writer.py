"""RecordIO dataset conversion (python/paddle/fluid/recordio_writer.py
parity): serialize a Fluid reader's samples into a recordio file that the
graph-reader layers (layers.io.open_recordio_file / open_files) consume.

Storage format per record: little-endian uint32 field count, then per
field uint32 length + the field's .npy bytes (language-neutral — the C++
runtime reads the same container via native/src/recordio.h + npy.h).
"""

import io
import struct

import numpy as np

from paddle_tpu import native

__all__ = [
    "convert_reader_to_recordio_file",
    "convert_reader_to_recordio_files",
    "pack_sample",
    "unpack_sample",
]


def pack_sample(sample):
    """tuple/list of arrays -> bytes."""
    fields = [np.asarray(f) for f in sample]
    out = io.BytesIO()
    out.write(struct.pack("<I", len(fields)))
    for f in fields:
        buf = io.BytesIO()
        np.save(buf, f, allow_pickle=False)
        raw = buf.getvalue()
        out.write(struct.pack("<I", len(raw)))
        out.write(raw)
    return out.getvalue()


def unpack_sample(blob):
    """bytes -> tuple of arrays."""
    view = memoryview(blob)
    (n,) = struct.unpack_from("<I", view, 0)
    off = 4
    fields = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", view, off)
        off += 4
        buf = io.BytesIO(bytes(view[off:off + ln]))
        fields.append(np.load(buf, allow_pickle=False))
        off += ln
    return tuple(fields)


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None):
    """Write every sample of ``reader_creator()`` into ``filename``.
    Returns the number of records written. ``feeder`` (a DataFeeder) may
    pre-convert samples, as in the reference API."""
    count = 0
    with native.RecordIOWriter(filename) as w:
        for sample in reader_creator():
            if feeder is not None:
                fed = feeder.feed([sample])
                sample = tuple(fed[k] for k in feeder.feed_names)
            w.write(pack_sample(sample))
            count += 1
    return count


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder=None):
    """Shard into multiple files of ``batch_per_file`` records each
    (reference convert_reader_to_recordio_files); returns the file list."""
    paths = []
    writer = None
    n_in_file = 0
    count = 0
    try:
        for sample in reader_creator():
            if writer is None:
                path = "%s-%05d" % (filename, len(paths))
                paths.append(path)
                writer = native.RecordIOWriter(path)
                n_in_file = 0
            if feeder is not None:
                fed = feeder.feed([sample])
                sample = tuple(fed[k] for k in feeder.feed_names)
            writer.write(pack_sample(sample))
            count += 1
            n_in_file += 1
            if n_in_file >= batch_per_file:
                writer.close()
                writer = None
    finally:
        if writer is not None:
            writer.close()
    return paths
