"""DataFeeder: convert minibatch sample lists -> feed dict of arrays.

Reference parity: python/paddle/fluid/data_feeder.py — converts python/numpy
minibatch data into LoDTensors per feed var; here, lod_level>0 slots become
dense padded arrays + the LoD kept on a host LoDTensor (converted at the
executor feed boundary; SURVEY.md §5.7 bucketing note).
"""

import numpy as np

from paddle_tpu import framework
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.types import np_dtype


class DataToLoDTensorConverter(object):
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.dtype = np_dtype(dtype)
        self.data = []
        self.lod = [[] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            # Reshape samples to the declared per-sample shape when static.
            sample_shape = [int(d) for d in self.shape[1:]] if self.shape else []
            if sample_shape and all(d >= 0 for d in sample_shape):
                arr = arr.reshape([len(self.data)] + sample_shape)
            return LoDTensor(arr)
        flat = [np.asarray(x, dtype=self.dtype) for x in self.data]
        arr = (
            np.concatenate([f.reshape(-1, *f.shape[1:]) if f.ndim else f.reshape(1)
                            for f in flat])
            if flat
            else np.zeros((0,), self.dtype)
        )
        # build offsets from recursive lengths
        t = LoDTensor(arr)
        t.set_recursive_sequence_lengths(self.lod)
        return t


class DataFeeder(object):
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or framework.default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, framework.Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            shape = each_var.shape or ()
            self.feed_shapes.append([d for d in shape if d >= 0] and list(shape))
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(
                self.place,
                lod_level=self.feed_lod_level[i],
                shape=self.feed_shapes[i],
                dtype=self.feed_dtypes[i],
            )
            for i in range(len(self.feed_names))
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "sample has %d slots, feeder expects %d"
                % (len(each_sample), len(converters))
            )
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            t = each_converter.done()
            ret_dict[each_name] = t if t.lod() else t.numpy()
        return ret_dict

    def feed_parallel(self, iterable, num_places=None):
        """One feed dict per place from per-place sample iterables
        (data_feeder.py feed_parallel parity). ``iterable`` holds one
        minibatch iterable per device; ParallelExecutor.run accepts the
        resulting list and concatenates along the batch axis."""
        if num_places is not None and len(iterable) != int(num_places):
            raise ValueError(
                "feed_parallel got %d iterables for %d places"
                % (len(iterable), int(num_places)))
        return [self.feed(batch) for batch in iterable]

    def _num_places(self, num_places):
        if num_places is not None:
            return int(num_places)
        import jax

        return jax.local_device_count()

    def decorate_reader(self, reader, multi_devices=True, num_places=None,
                        drop_last=True):
        """Wrap a batch-level reader into feed dicts (decorate_reader
        parity): each yielded item becomes one feed dict, or a list of
        per-device dicts with the batch split evenly when
        ``multi_devices``. An indivisible batch is truncated to the
        largest device multiple (only the remainder SAMPLES drop; a
        batch smaller than the device count drops whole) when
        ``drop_last``, else raises."""
        n = self._num_places(num_places) if multi_devices else 1

        def decorated():
            for batch in reader():
                if not multi_devices:
                    yield self.feed(batch)
                    continue
                usable = (len(batch) // n) * n
                if usable != len(batch) and not drop_last:
                    raise ValueError(
                        "batch size %d not divisible by %d devices and "
                        "drop_last=False" % (len(batch), n))
                if usable == 0:
                    continue
                per = usable // n
                yield [self.feed(batch[i * per:(i + 1) * per])
                       for i in range(n)]

        return decorated
