"""Classified retry policy: transient failures backed off and retried,
user errors surfaced immediately, every retry counted and filed.

The failure taxonomy production TPU fleets actually produce splits
cleanly in two. *Transient*: a flaky NFS read under the persistent exec
cache, an RPC reset while the elastic master restarts, a preempted
backend compile — retrying after a backoff is the correct (and only)
remedy. *Permanent*: a verifier diagnostic, a shape mismatch, a NaN trip
— retrying re-executes the same deterministic failure and burns
accelerator-hours hiding the real bug. The reference leans on brpc
channel retries for the first class and PADDLE_ENFORCE fail-fast for the
second; this module is that split as one reusable policy, applied to the
executor's fresh-compile/dispatch paths, exec-cache reads and
``MasterClient._call``.

Policy: up to ``FLAGS_dispatch_retries`` retries, exponential backoff
(``FLAGS_retry_backoff_s`` * 2^attempt) with up to 50% jitter so a fleet
of preempted workers doesn't stampede a recovering master. Every retry
increments ``paddle_tpu_retries_total{origin}`` and, when the black box
is armed, files a ``retry`` flight event — a run that silently survived
three IO faults is an incident report, not a clean run.

Donation safety: XLA dispatch donates the state buffers; a dispatch that
died *after* consuming them cannot be retried (the retry would crash on
deleted arrays and mask the original error). Callers pass the donated
pytree via ``donated=``; the policy re-raises instead of retrying once
any leaf reports deleted.
"""

import random
import time

from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "TransientError", "is_transient", "call", "retries_enabled",
]

# substrings of RPC-ish status messages worth retrying when they arrive
# wrapped in a backend RuntimeError instead of a typed OSError.
# RESOURCE_EXHAUSTED is deliberately NOT here: an XLA allocator OOM is
# deterministic for a given program and batch — retrying replays the
# same death N times, burning the budget AND the accelerator-hours
# (observability/memory.py classifies it, rule M001).
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED",
    "connection reset", "temporarily unavailable",
)

_retries_total = REGISTRY.counter(
    "paddle_tpu_retries_total", "transient-failure retries by origin",
    ["origin"])
_exhausted_total = REGISTRY.counter(
    "paddle_tpu_retries_exhausted_total",
    "operations that failed even after the full retry budget", ["origin"])


class TransientError(RuntimeError):
    """Raise (or wrap with) this to mark a failure explicitly retryable
    regardless of its concrete type."""


# OSErrors that are deterministic configuration/programming failures, not
# infrastructure flake: retrying replays them verbatim
_PERMANENT_OS_ERRORS = (FileNotFoundError, PermissionError,
                        IsADirectoryError, NotADirectoryError)


def is_transient(exc):
    """The classification table (docs/RESILIENCE.md):

    retry     ChaosIOError/ChaosTransientError (injected), TransientError,
              ConnectionError/EOFError/TimeoutError, OSError/IOError
              (except the deterministic kinds: missing path, permission,
              not-a-directory), RuntimeErrors carrying RPC status markers
              (UNAVAILABLE...)
    never     ProgramVerifyError, NaN/Inf trips (deterministic replays),
              RESOURCE_EXHAUSTED/OOM (deterministic allocator deaths —
              rule M001, observability/memory.py),
              ValueError/TypeError/KeyError/AssertionError (user errors —
              including ``distributed.master.AuthError``: a credential
              rejection replays verbatim until the token changes),
              FileNotFoundError/PermissionError and kin, everything else
    """
    from paddle_tpu.observability.memory import is_oom
    from paddle_tpu.resilience.chaos import (
        ChaosIOError, ChaosTransientError)

    if isinstance(exc, (TransientError, ChaosIOError,
                        ChaosTransientError)):
        return True
    if is_oom(exc):
        # checked BEFORE the marker scan: the same program at the same
        # batch OOMs the same way every attempt — a retry budget spent
        # here masks the real fix (donate, shrink, shard)
        return False
    if isinstance(exc, (ValueError, TypeError, KeyError, AssertionError)):
        return False
    try:
        from paddle_tpu.analysis import ProgramVerifyError

        if isinstance(exc, ProgramVerifyError):
            return False
    except Exception:
        pass
    msg = str(exc)
    if "NaN/Inf" in msg:  # NonFiniteError keeps this marker (PR 4)
        return False
    if isinstance(exc, _PERMANENT_OS_ERRORS):
        return False
    if isinstance(exc, (ConnectionError, EOFError, TimeoutError, OSError)):
        return True
    if isinstance(exc, RuntimeError):
        return any(m in msg for m in _TRANSIENT_MARKERS)
    return False


def retries_enabled():
    from paddle_tpu import flags

    try:
        return int(flags.get("dispatch_retries")) > 0
    except (KeyError, TypeError, ValueError):
        return False


def _backoff_s(attempt):
    from paddle_tpu import flags

    try:
        base = float(flags.get("retry_backoff_s"))
    except (KeyError, TypeError, ValueError):
        base = 0.05
    if base <= 0:
        return 0.0
    return base * (2 ** attempt) * (1.0 + 0.5 * random.random())


def _donation_consumed(donated):
    if donated is None:
        return False
    import jax

    return any(
        getattr(leaf, "is_deleted", lambda: False)()
        for leaf in jax.tree_util.tree_leaves(donated))


def call(fn, origin="work", donated=None, retries=None, classify=None):
    """Run ``fn()`` under the retry policy. ``retries=None`` reads
    ``FLAGS_dispatch_retries`` (0 = call straight through — the default
    hot path adds one flag read and nothing else). ``classify``
    overrides :func:`is_transient`. ``donated``: pytree whose leaves,
    once consumed by a failed dispatch, veto the retry."""
    if retries is None:
        from paddle_tpu import flags

        try:
            retries = int(flags.get("dispatch_retries"))
        except (KeyError, TypeError, ValueError):
            retries = 0
    if retries <= 0:
        return fn()
    classify = classify or is_transient
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 - reclassified below
            if (attempt >= retries or not classify(exc)
                    or _donation_consumed(donated)):
                if attempt > 0:
                    _exhausted_total.inc(origin=origin)
                raise
            delay = _backoff_s(attempt)
            attempt += 1
            _retries_total.inc(origin=origin)
            from paddle_tpu.observability import blackbox

            if blackbox.ENABLED:
                blackbox.record(
                    "retry", origin=origin, attempt=attempt,
                    backoff_s=round(delay, 4),
                    exc_type=type(exc).__name__,
                    exc_message=str(exc)[:500])
            if delay > 0:
                time.sleep(delay)
