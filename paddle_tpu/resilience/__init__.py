"""Resilience: the layer that turns failure detection into recovery.

PR 4's observability stack (black box, watchdog, NaN provenance) made
failures *explained*; this package makes them *survived* — the fault-
tolerance contract (consistent checkpointing + automatic recovery) the
TensorFlow system paper (Abadi et al., 2016) names as table stakes for
production training on preemptible fleets, and the recovery half the
elastic master (``distributed/master.py``) has always assumed exists:

* ``checkpoint`` — :class:`CheckpointManager`: atomic (temp dir +
  fsynced manifest + rename), digest-verified, asynchronously written
  checkpoints capturing scope state AND the executor RNG stream; on
  load, corrupt serials are quarantined and the scan falls back to the
  newest *complete* one.
* ``session`` — :class:`TrainSession`: owns the training loop's
  resilience — periodic checkpoints, SIGTERM/SIGINT = finish the step,
  checkpoint, die by the signal; auto-resume with a bit-identical loss
  trajectory; emergency checkpoint on a watchdog-declared hang.
* ``retry`` — classified retry policy: transient IO/RPC/exec-cache
  failures backed off and retried (``FLAGS_dispatch_retries``),
  user/verifier errors never; every retry counted
  (``paddle_tpu_retries_total``) and filed to the black box.
* ``chaos`` — seeded, deterministic fault injection
  (``FLAGS_chaos_spec``): kill-points and injected IO/compile/slow
  faults at named sites, the harness the crash/resume tests and the CI
  ``chaos`` stage drive.

``docs/RESILIENCE.md`` is the operator's guide (checkpoint format,
retry classification table, chaos grammar, metrics catalog).
"""

from paddle_tpu.resilience import chaos  # noqa: F401
from paddle_tpu.resilience import checkpoint  # noqa: F401
from paddle_tpu.resilience import retry  # noqa: F401
from paddle_tpu.resilience import session  # noqa: F401
from paddle_tpu.resilience.checkpoint import CheckpointManager  # noqa: F401
from paddle_tpu.resilience.session import TrainSession  # noqa: F401
