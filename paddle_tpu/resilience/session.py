"""Preemption-safe training sessions: periodic checkpoints, graceful
signal death, automatic resume.

The elastic master (``distributed/master.py``) already assumes workers
die and come back — leased tasks time out and requeue. What it cannot do
is give a returned worker its *model state* back. :class:`TrainSession`
is that other half: a thin loop owner around ``Executor.run`` that

* **auto-resumes** on construction from the newest *verified* serial in
  ``checkpoint_dir`` (corrupt ones quarantined by the manager), restoring
  parameters, optimizer accumulators, LR counters AND the executor's RNG
  stream — a killed-and-restarted process continues at the right step
  with a loss trajectory bit-identical to the run that never died;
* **checkpoints periodically** (``FLAGS_checkpoint_interval_steps`` /
  ``_secs``, or constructor args), asynchronously — the step pays for a
  device→host snapshot, never for disk;
* **dies gracefully**: a SIGTERM/SIGINT (the preemption notice) lets the
  in-flight step finish, writes a final checkpoint, then restores the
  previous handler and re-delivers the signal — composing with the black
  box's handler chain (blackbox dumps, then the process still dies BY
  the signal, as supervisors require);
* **saves on hangs**: registered with the watchdog, a declared hang
  triggers an emergency checkpoint *before* ``FLAGS_watchdog_abort``
  kills the process — the stall costs a restart, not the training run.

Usage::

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)                       # init scope FIRST
    with TrainSession(exe, "ckpt/", main_program=main) as sess:
        while sess.step < total_steps:
            loss, = sess.run(feed=next_batch(sess.step),
                             fetch_list=[loss_var])

``sess.run`` is also a chaos kill-point (``session.step``): the CI chaos
stage SIGKILLs a child at a seeded step and asserts the restarted child
reproduces the uninterrupted run exactly.
"""

import signal
import threading
import time

from paddle_tpu import framework
from paddle_tpu.resilience import chaos
from paddle_tpu.resilience.checkpoint import CheckpointManager

__all__ = ["TrainSession"]

_HANDLED_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class TrainSession(object):
    def __init__(self, executor, checkpoint_dir, main_program=None,
                 scope=None, interval_steps=None, interval_secs=None,
                 max_to_keep=None, auto_resume=True,
                 install_signal_handlers=True, emergency_on_hang=True,
                 manager=None):
        from paddle_tpu import flags

        self._exe = executor
        self._program = main_program or framework.default_main_program()
        self._scope = scope
        if interval_steps is None:
            interval_steps = int(flags.get("checkpoint_interval_steps"))
        if interval_secs is None:
            interval_secs = float(flags.get("checkpoint_interval_secs"))
        self.interval_steps = int(interval_steps)
        self.interval_secs = float(interval_secs)
        # an injected manager (e.g. elastic/reshard.py's
        # ShardedCheckpointManager, whose var files are laid out by the
        # mesh's sharding plan) replaces the default; it must already be
        # bound to this executor/program/scope
        self.manager = manager if manager is not None else CheckpointManager(
            checkpoint_dir, executor=executor, main_program=self._program,
            scope=scope, max_to_keep=max_to_keep)
        self.step = 0
        self.resumed_serial = None
        if auto_resume:
            manifest = self.manager.restore()
            if manifest is not None:
                self.step = int(manifest.get("step", 0))
                self.resumed_serial = int(manifest["serial"])
        self._last_save_step = self.step
        self._last_save_time = time.monotonic()
        self._stop_signum = None
        self._in_step = False
        self._closed = False
        self._prev_handlers = {}
        self._hang_cb = None
        if install_signal_handlers:
            self._install_signal_handlers()
        if emergency_on_hang:
            from paddle_tpu.observability import watchdog

            self._hang_cb = watchdog.register_on_hang(self._on_hang)

    # -- the step -----------------------------------------------------------

    def run(self, feed=None, fetch_list=None, program=None, **kwargs):
        """One training step: ``Executor.run`` plus session bookkeeping.
        After the step completes, a pending preemption signal finalizes
        (final checkpoint, handler restored, signal re-delivered) — the
        step in flight when SIGTERM lands is never torn."""
        if self._closed:
            raise RuntimeError("TrainSession is closed")
        if chaos.ENABLED:
            chaos.fault("session.step", step=self.step)
        self._in_step = True
        try:
            out = self._exe.run(
                program or self._program, feed=feed,
                fetch_list=fetch_list, scope=self._scope, **kwargs)
            # the step-counter bump is part of the "in step" window: a
            # signal landing between the executor returning and the bump
            # must defer to the post-step finalize below, or the handler
            # would checkpoint step N-1's count over step N's state and
            # RNG counter — a torn manifest that breaks exact resume
            self.step += 1
        finally:
            self._in_step = False
            import sys

            if (self._stop_signum is not None
                    and sys.exc_info()[0] is not None):
                # the step the preemption deferred to has RAISED: the
                # signal must not be swallowed by the exception path —
                # bank the pre-step state and die by the signal (step
                # counter was never bumped, so the checkpoint is
                # consistent with the last completed step)
                self._finalize_and_reraise()
        if self._stop_signum is not None:
            self._finalize_and_reraise()
        elif self._checkpoint_due():
            self.save(final=False)
        return out

    def _checkpoint_due(self):
        if (self.interval_steps > 0
                and self.step - self._last_save_step
                >= self.interval_steps):
            return True
        if (self.interval_secs > 0
                and time.monotonic() - self._last_save_time
                >= self.interval_secs):
            return True
        return False

    # -- checkpointing ------------------------------------------------------

    def save(self, final=True):
        """Write a checkpoint at the current step: synchronously when
        ``final`` (the caller is about to exit — the write must land),
        asynchronously otherwise. Returns the serial."""
        if final:
            self.manager.save(self.step)
        else:
            self.manager.save_async(self.step)
        self._last_save_step = self.step
        self._last_save_time = time.monotonic()
        return self.step

    def should_stop(self):
        """True once a preemption signal has been received (readable from
        data-loading code between steps)."""
        return self._stop_signum is not None

    # -- preemption plumbing ------------------------------------------------

    def _install_signal_handlers(self):
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal raises off-main; sessions there skip it
        for sig in _HANDLED_SIGNALS:
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._signal_handler)
            except (ValueError, OSError):
                pass

    def _uninstall_signal_handlers(self):
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_handlers = {}

    def _signal_handler(self, signum, frame):
        self._stop_signum = signum
        from paddle_tpu.observability import blackbox

        if blackbox.ENABLED:
            blackbox.record("preemption_signal", signal=int(signum),
                            step=self.step, in_step=self._in_step)
        if not self._in_step:
            # idle (between steps / in data loading): nothing to finish,
            # finalize right here in handler context
            self._finalize_and_reraise()
        # else: run() finalizes after the in-flight step returns

    def _finalize_and_reraise(self):
        signum = self._stop_signum
        try:
            self.manager.save(self.step)
        except Exception:
            # the signal must still propagate even if the final save
            # failed (metrics/blackbox already recorded the failure)
            pass
        self.close(save=False)
        # re-deliver through the PREVIOUS handler chain: the black box's
        # handler (if armed) dumps and re-raises, supervisors still see
        # a death by signal / KeyboardInterrupt semantics for SIGINT
        import os

        os.kill(os.getpid(), signum)

    def _on_hang(self, report):
        """Watchdog thread: the main thread is wedged, FLAGS_watchdog_abort
        may be about to kill the process — bank the training state first.
        ONLY when the hang is outside a step (a deadlocked input
        pipeline, wedged user code): mid-dispatch the scope's mutable
        state is donated to the stuck executable — its buffers may
        already be deleted, and a 'successful' save would bank a
        parameter-less checkpoint that wins as newest serial. In that
        case the last periodic checkpoint is the best consistent state
        there is, and skipping also keeps this thread from blocking on
        the wedged runtime and holding off the abort."""
        from paddle_tpu.observability import blackbox

        if self._in_step:
            if blackbox.ENABLED:
                blackbox.record(
                    "emergency_checkpoint_skipped", step=self.step,
                    reason="hang is mid-dispatch; scope state is donated")
            return
        try:
            if blackbox.ENABLED:
                blackbox.record("emergency_checkpoint", step=self.step,
                                reason="watchdog_hang")
            self.manager.save(self.step)
        except Exception:
            pass  # a failed emergency save must not mask the hang report

    # -- lifecycle ----------------------------------------------------------

    def close(self, save=True):
        """Detach handlers and (by default) write a final synchronous
        checkpoint. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if save:
            try:
                self.manager.save(self.step)
            except Exception:
                pass
        else:
            self.manager.wait()
        self._uninstall_signal_handlers()
        if self._hang_cb is not None:
            from paddle_tpu.observability import watchdog

            watchdog.unregister_on_hang(self._hang_cb)
            self._hang_cb = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # clean exit banks the final state; an exception keeps the last
        # periodic checkpoint (saving mid-exception could bank a step
        # that never logically completed)
        self.close(save=exc_type is None)
        return False
