"""Deterministic fault injection: the chaos harness recovery code is
proved against.

A recovery layer that has never seen a failure is decoration. This module
arms *seeded, reproducible* faults at named sites inside the framework —
the executor's fresh-compile and dispatch paths, the checkpoint writer,
the master client — so the crash/resume tests and the CI ``chaos`` stage
exercise the exact code paths production preemption and flaky IO will.

Spec grammar (``FLAGS_chaos_spec``)::

    spec    := clause (';' clause)*
    clause  := 'seed=' INT                      -- RNG seed for p= draws
             | kind '@' param (',' param)*
    kind    := 'kill' | 'io' | 'compile' | 'slow' | 'oom'
    param   := 'site=' NAME    -- site to arm (default: kind's home site)
             | 'step=' INT     -- fire exactly when the caller's step == N
             | 'p=' FLOAT      -- fire probability per visit (seeded draw)
             | 'n=' INT        -- total fire budget (default: kill 1, else
                                  unlimited)
             | 'skip=' INT     -- ignore the first K visits to the site
                                  (deterministic "fail LATER" at sites
                                  that don't pass a step number, e.g.
                                  exec.dispatch after warmup steps)
             | 'secs=' FLOAT   -- sleep length (slow only, default 0.1)

Examples::

    kill@step=7                       # SIGKILL self entering step 7
    kill@site=ckpt.write,n=1          # die mid-checkpoint-write, once
    io@site=ckpt.write,p=0.5          # checkpoint writes fail half the time
    compile@n=2;seed=11               # first two fresh compiles fail
    slow@site=exec.dispatch,p=0.1,secs=0.3

Sites instrumented today: ``session.step`` (kill-point at the top of every
``TrainSession.run``), ``ckpt.write`` (after var files, before the
manifest/rename — a kill here leaves a temp dir a restart must ignore),
``exec.compile`` (fresh-compile path), ``exec.dispatch`` (executor step
dispatch), ``master.call`` (MasterClient RPC), ``aot.read`` (persistent
exec-cache image load), the fleet coordinator RPCs as
``fleet.<method>`` — ``fleet.heartbeat`` and ``fleet.register`` are the
documented churn-injection points (a seeded fault at either exercises
the eviction/rejoin path the elastic runtime recovers through) — and
the serving sites: ``serve.dispatch`` (the BatchingServer batch
dispatch AND the decode session's step dispatch, which passes
``step=steps_done`` so ``kill@site=serve.dispatch,step=N`` SIGKILLs a
decoding process deterministically — the servechaos CI leg),
``serve.admit`` (inside a slot admission, after slots/pages are claimed
and before the dispatch — a fault here must roll the whole group back
and, under retry, re-admit bit-identically), ``pool.acquire`` (the KV
page allocator), ``snapshot.write`` (between a decode snapshot's
var files, beside the inherited ``ckpt.write`` — a kill mid-snapshot
must be invisible to the next restore), and the network front end's
wire sites in ``distributed/master.py``'s ``serve_json_lines``:
``net.accept`` (sever a just-accepted connection before any request is
read — the client must reconnect) and ``net.send`` (fail a response
write mid-stream, severing the connection — arm the ``io`` kind; the
client must retry a unary call / surface a typed StreamBrokenError on
a broken stream, never hang). The router tier (``serving/router.py``)
adds ``router.route`` (inside member selection for one admission —
an ``io`` fault here must re-route under classified retry, and a
``kill`` takes the router down mid-admission), ``migrate.ship``
(before a migration's snapshot payload is shipped to the target
frontend — a ``kill`` here is the mid-migration router death the
failure matrix covers: the snapshot is still banked on disk, a
restarted router re-runs the migration idempotently) and
``migrate.restore`` (before the target is told to restore the shipped
payload — an ``io`` fault must retry the restore RPC, never lose the
stream).

Determinism: each clause owns a ``random.Random`` seeded by
``(seed, clause index)``, advanced once per visit to its site — a fixed
spec against a fixed single-threaded training loop fires at the same
steps every run, which is what lets the chaos CI stage assert *exact*
resume behavior instead of flaky approximations.

Injected faults raise :class:`ChaosIOError` (an ``IOError``) or
:class:`ChaosTransientError` — both classified retryable by
``resilience/retry.py``, so a run with retries enabled must *survive*
them and a run without must die loudly. The ``oom`` kind raises
:class:`ChaosOOMError`, a RESOURCE_EXHAUSTED-style failure classified
NEVER-transient: a run with retries enabled must die on the FIRST
attempt (no budget burned on a deterministic allocator death) and leave
an M001 black-box dump (observability/memory.py). Every fire is counted
(``paddle_tpu_chaos_faults_total{site,kind}``) and filed to the black
box, so a test can prove the fault actually happened rather than pass
vacuously. ``ENABLED`` is a module bool: with the flag unset every
instrumented site costs one attribute load.
"""

import os
import random
import signal
import threading
import time

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "ChaosIOError", "ChaosTransientError", "ChaosOOMError",
    "configure", "disable", "fault", "clauses", "fires",
]

ENABLED = False


class ChaosIOError(IOError):
    """Injected IO failure (classified transient by resilience.retry)."""


class ChaosTransientError(RuntimeError):
    """Injected transient runtime failure (compile/dispatch/RPC)."""


class ChaosOOMError(RuntimeError):
    """Injected RESOURCE_EXHAUSTED: deterministic, classified
    never-transient (observability/memory.py M001 path)."""


_KINDS = ("kill", "io", "compile", "slow", "oom")
_HOME_SITE = {"kill": "session.step", "compile": "exec.compile"}

_lock = lock_witness.make_lock("resilience.chaos")
_clauses = []  # [{"kind", "site", "step", "p", "n", "secs", "rng", "fired"}]

_faults_total = REGISTRY.counter(
    "paddle_tpu_chaos_faults_total", "injected chaos faults by site",
    ["site", "kind"])


def _parse_clause(text, index, seed):
    kind, _, params = text.partition("@")
    kind = kind.strip()
    if kind not in _KINDS:
        raise ValueError(
            "chaos_spec: unknown fault kind %r (valid: %s)"
            % (kind, ", ".join(_KINDS)))
    c = {"kind": kind, "site": _HOME_SITE.get(kind), "step": None,
         "p": None, "n": 1 if kind == "kill" else None, "secs": 0.1,
         "skip": 0, "visits": 0,
         # int-mixed per-clause stream: deterministic across processes
         # (unlike tuple seeding, which hashes) and independent per clause
         "rng": random.Random(seed * 1000003 + index), "fired": 0}
    for param in filter(None, (p.strip() for p in params.split(","))):
        k, _, v = param.partition("=")
        k = k.strip()
        if k == "site":
            c["site"] = v.strip()
        elif k == "step":
            c["step"] = int(v)
        elif k == "p":
            c["p"] = float(v)
        elif k == "n":
            c["n"] = int(v)
        elif k == "skip":
            c["skip"] = int(v)
        elif k == "secs":
            c["secs"] = float(v)
        else:
            raise ValueError("chaos_spec: unknown param %r in %r"
                             % (k, text))
    if c["site"] is None:
        raise ValueError(
            "chaos_spec: %r needs an explicit site= (only %s have a "
            "default site)" % (text, sorted(_HOME_SITE)))
    if c["step"] is None and c["p"] is None:
        c["p"] = 1.0  # bare "io@site=x" fires every visit (up to n)
    return c


def configure(spec=None):
    """Parse and arm ``spec`` (default: ``FLAGS_chaos_spec``). An empty
    spec disarms. Returns the parsed clause list (tests)."""
    global ENABLED
    if spec is None:
        from paddle_tpu import flags

        spec = flags.get("chaos_spec")
    with _lock:
        _clauses[:] = []
        if not spec:
            ENABLED = False
            return []
        parts = [p.strip() for p in str(spec).split(";") if p.strip()]
        seed = 0
        for p in parts:
            if p.startswith("seed="):
                seed = int(p[len("seed="):])
        for i, p in enumerate(parts):
            if p.startswith("seed="):
                continue
            _clauses.append(_parse_clause(p, i, seed))
        ENABLED = bool(_clauses)
        return [dict(c, rng=None) for c in _clauses]


def disable():
    configure("")


def clauses():
    """Parsed clauses with live fire counts (introspection/tests)."""
    with _lock:
        return [dict(c, rng=None) for c in _clauses]


def fires(site=None):
    """Total faults fired (optionally for one site)."""
    with _lock:
        return sum(c["fired"] for c in _clauses
                   if site is None or c["site"] == site)


def _record(site, kind):
    _faults_total.inc(site=site, kind=kind)
    from paddle_tpu.observability import blackbox

    if blackbox.ENABLED:
        blackbox.record("chaos_fault", site=site, fault=kind)


def fault(site, step=None):
    """The kill-point: every instrumented site calls this (guarded on
    ``ENABLED``). Raises/kills/sleeps according to armed clauses; a
    no-match visit costs one lock + list scan, paid only while chaos is
    configured."""
    fire = None
    # Timed acquire [C003]: the ckpt.write site sits inside the SIGTERM
    # handler chain chaos runs deliberately exercise, and the signal may
    # have interrupted this very thread mid-scan. Uncontended (the only
    # deterministic case the schedules rely on) the acquire is
    # immediate; on timeout the visit is skipped rather than deadlock.
    if not _lock.acquire(timeout=5.0):
        return
    try:
        for c in _clauses:
            if c["site"] != site:
                continue
            if c["n"] is not None and c["fired"] >= c["n"]:
                continue
            c["visits"] += 1
            if c["visits"] <= c["skip"]:
                continue
            if c["step"] is not None:
                if step is None or int(step) != c["step"]:
                    continue
            elif c["p"] is not None and c["rng"].random() >= c["p"]:
                continue
            c["fired"] += 1
            fire = (c["kind"], c["secs"])
            break
    finally:
        _lock.release()
    if fire is None:
        return
    kind, secs = fire
    _record(site, kind)
    if kind == "kill":
        # SIGKILL, not SystemExit: the preemption being simulated gives
        # no cleanup opportunity — that is the entire point
        os.kill(os.getpid(), signal.SIGKILL)
    elif kind == "io":
        raise ChaosIOError("chaos: injected IO failure at %s" % site)
    elif kind == "compile":
        raise ChaosTransientError(
            "chaos: injected transient failure at %s" % site)
    elif kind == "oom":
        # the XLA allocator's status wording, so every layer that keys
        # on RESOURCE_EXHAUSTED (retry veto, M001 enrichment) treats the
        # injected fault exactly like the real one
        raise ChaosOOMError(
            "RESOURCE_EXHAUSTED: chaos: injected out-of-memory at %s"
            % site)
    elif kind == "slow":
        time.sleep(secs)


def _init_from_flags():
    try:
        configure()
    except Exception:
        # a malformed spec must not mask the import; surface it loudly
        # but once, then stay disabled
        import logging

        logging.getLogger("paddle_tpu.resilience.chaos").exception(
            "FLAGS_chaos_spec is malformed; chaos disabled")


_init_from_flags()
