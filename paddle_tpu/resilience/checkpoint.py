"""Checkpoint v2: atomic, digest-verified, asynchronously written,
corruption-tolerant on load.

``io.save_checkpoint`` (v1) writes var files straight into the final
directory: a crash mid-save leaves a partial dir that ``load_checkpoint``
happily returns as "latest". This manager closes every hole in that
story, following the consistent-checkpointing discipline the TensorFlow
paper (Abadi et al., 2016) names as the fault-tolerance mechanism for
production training:

* **Atomic**: vars are written to ``checkpoint_<serial>.tmp-<pid>``, the
  manifest is fsynced, and the directory is atomically renamed. A
  checkpoint either exists completely or not at all; temp dirs from a
  killed writer are ignored (and swept) on the next restore.
* **Verified**: the manifest carries a sha256 digest of every var file.
  ``restore`` re-hashes before loading; a flipped bit is detected, the
  corrupt serial is *quarantined* (renamed ``.corrupt-<n>``, never
  deleted — it is forensic evidence), and the scan falls back to the
  next-newest complete serial.
* **Asynchronous**: ``save_async`` snapshots device arrays to host on
  the calling thread (the only part the training step waits for) and
  hands hashing + disk IO to a background writer; back-to-back saves
  serialize on the previous write.
* **Complete**: besides every persistable var in scope — which already
  includes optimizer accumulators, batch-norm stats and the
  ``@LR_DECAY_COUNTER@`` the LR schedulers key on — the manifest records
  the executor's RNG state (base seed + run counter, the inputs to the
  per-step ``fold_in`` key), so a resumed process replays the *identical*
  dropout masks and sampling the uninterrupted run would have used:
  loss-trajectory bit-equality, not just approximate resumption.

Layout (readable by ``io.load_checkpoint`` and ``tools/ckpt_inspect.py``)::

    <dir>/checkpoint_<serial>/
        <var-name>.npy ...            # '/' in names becomes '__'
        __manifest__.json             # schema in docs/RESILIENCE.md

Metrics: ``paddle_tpu_checkpoint_save_seconds`` (histogram, full write),
``paddle_tpu_checkpoint_bytes`` (gauge, last save),
``paddle_tpu_checkpoint_failures_total{stage}`` and
``paddle_tpu_checkpoint_restores_total{outcome}``.
"""

import hashlib
import json
import os
import shutil
import threading
import time

import numpy as np

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY
from paddle_tpu.resilience import chaos

__all__ = ["CheckpointManager", "MANIFEST_NAME", "read_manifest",
           "verify_checkpoint_dir", "complete_serials", "assemble_var"]

MANIFEST_NAME = "__manifest__.json"
MANIFEST_VERSION = 2

_save_seconds = REGISTRY.histogram(
    "paddle_tpu_checkpoint_save_seconds",
    "wall seconds per checkpoint write (snapshot excluded)")
_save_bytes = REGISTRY.gauge(
    "paddle_tpu_checkpoint_bytes", "bytes written by the last checkpoint")
_failures = REGISTRY.counter(
    "paddle_tpu_checkpoint_failures_total",
    "checkpoint save/load failures by stage", ["stage"])
_restores = REGISTRY.counter(
    "paddle_tpu_checkpoint_restores_total",
    "checkpoint restore attempts by outcome", ["outcome"])


def _safe_name(var_name):
    return var_name.replace("/", "__")


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_dir(path):
    """Durability for the rename itself; best-effort on filesystems
    without directory fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def read_manifest(step_dir):
    """The parsed manifest of one checkpoint dir, or None (no/corrupt
    manifest = incomplete checkpoint)."""
    try:
        with open(os.path.join(step_dir, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_checkpoint_dir(step_dir, manifest=None):
    """Re-hash every var file against the manifest. Returns a list of
    human-readable problems (empty = verified). Manifests without digests
    (io.save_checkpoint's marker manifests) verify file presence only.
    Vars stored as shard files (elastic/reshard.py's sharded dialect,
    ``meta["shards"]``) verify every shard's digest AND that the shard
    bytes sum back to the var's logical bytes — a dropped shard must
    fail verification, never reassemble short."""
    manifest = manifest or read_manifest(step_dir)
    if manifest is None:
        return ["no readable %s" % MANIFEST_NAME]
    problems = []
    for name, meta in sorted(manifest.get("vars", {}).items()):
        shards = meta.get("shards")
        entries = shards if shards else [meta]
        shard_bytes = 0
        broken = False
        for ent in entries:
            fname = ent.get("file")
            if not fname:
                problems.append("no file recorded for var %r" % name)
                broken = True
                continue
            path = os.path.join(step_dir, fname)
            if not os.path.exists(path):
                problems.append("missing file for var %r: %s"
                                % (name, fname))
                broken = True
                continue
            want = ent.get("sha256")
            if want and _sha256_file(path) != want:
                problems.append("digest mismatch for var %r (%s)"
                                % (name, fname))
                broken = True
            shard_bytes += int(ent.get("bytes", 0))
        if (shards and not broken and meta.get("bytes") is not None
                and shard_bytes != int(meta["bytes"])):
            problems.append(
                "shard bytes for var %r sum to %d, manifest records %d"
                % (name, shard_bytes, int(meta["bytes"])))
    for fname in manifest.get("files", []):
        if not os.path.exists(os.path.join(step_dir, fname)):
            problems.append("missing file %s" % fname)
    return problems


def assemble_var(step_dir, meta):
    """One var's full host array from its manifest meta: a plain
    single-file var loads directly; a sharded var (``meta["shards"]``,
    written by elastic/reshard.py's ShardedCheckpointManager)
    concatenates its shard files along the recorded split axis. Both
    dialects load through every restore path — a checkpoint written
    under a 4-way mesh restores into a 1-device scope unchanged."""
    shards = meta.get("shards")
    if not shards:
        return np.load(os.path.join(step_dir, meta["file"]),
                       allow_pickle=False)
    pieces = [np.load(os.path.join(step_dir, s["file"]),
                      allow_pickle=False) for s in shards]
    if len(pieces) == 1:
        return pieces[0]
    return np.concatenate(pieces, axis=int(meta.get("shard_axis", 0)))


def complete_serials(checkpoint_dir):
    """Sorted serials whose dir holds a readable manifest. Temp dirs
    (``.tmp-<pid>``), quarantined dirs (``.corrupt-<n>``) and marker-less
    partials never qualify."""
    out = []
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return out
    for d in entries:
        if not d.startswith("checkpoint_"):
            continue
        suffix = d[len("checkpoint_"):]
        if not suffix.isdigit():
            continue
        if read_manifest(os.path.join(checkpoint_dir, d)) is not None:
            out.append(int(suffix))
    return sorted(out)


class CheckpointManager(object):
    """See module docstring. ``executor`` provides the RNG state to
    capture (and receive on restore); ``main_program`` narrows the saved
    set to its persistables (default: every array-valued var in scope)."""

    def __init__(self, checkpoint_dir, executor=None, main_program=None,
                 scope=None, max_to_keep=None):
        self.checkpoint_dir = str(checkpoint_dir)
        self._executor = executor
        self._program = main_program
        self._scope = scope
        if max_to_keep is None:
            from paddle_tpu import flags

            try:
                max_to_keep = int(flags.get("checkpoint_max_to_keep"))
            except (KeyError, TypeError, ValueError):
                max_to_keep = 3
        self.max_to_keep = max(1, int(max_to_keep))
        # serials retention must NEVER delete, regardless of age: the
        # elastic runtime pins a published reshape-barrier serial here
        # while late joiners may still be restoring it
        self.pinned_serials = set()
        self._write_lock = lock_witness.make_lock(
            "resilience.checkpoint.write")   # one writer at a time
        self._thread = None
        self.last_error = None
        self.last_saved_serial = None

    # -- capture ------------------------------------------------------------

    def _live_scope(self):
        if self._scope is not None:
            return self._scope
        from paddle_tpu.executor import global_scope

        return global_scope()

    def _var_names(self, scope):
        if self._program is not None:
            return [v.name for v in self._program.list_vars()
                    if getattr(v, "persistable", False)]
        names = []
        s = scope
        while s is not None:
            names.extend(s.local_var_names())
            s = s._parent
        return names

    def _rng_state(self):
        exe = self._executor
        if exe is None:
            return None
        base = getattr(exe, "_base_seed", None)
        counter = getattr(exe, "_run_counter", None)
        if base is None or counter is None:
            return None
        return {"base_seed": int(base), "run_counter": int(counter)}

    def _snapshot(self, scope):
        """Host copies of every saveable var — the ONLY part of a save
        the training thread waits for. Non-array scope values (rank
        tables, reader state) are skipped: they are rebuilt by user
        code, not persisted."""
        snap = {}
        for name in self._var_names(scope):
            val = scope.get_value(name)
            if val is None:
                continue
            is_deleted = getattr(val, "is_deleted", None)
            if is_deleted is not None and is_deleted():
                # a donated buffer consumed by an in-flight dispatch: a
                # snapshot NOW would silently drop this var and bank a
                # verified-but-parameter-less checkpoint — fail the save
                raise RuntimeError(
                    "checkpoint snapshot: var %r holds a deleted "
                    "(donated) device buffer — the scope is mid-dispatch "
                    "and not snapshottable" % name)
            try:
                arr = np.asarray(val)
            except Exception:
                if hasattr(val, "shape") and hasattr(val, "dtype"):
                    raise  # an array that won't materialize is a failure
                continue  # non-array scope value (rank table, reader...)
            if arr.dtype == object or arr.dtype.kind in "OU":
                continue
            snap[name] = arr
        return snap

    # -- save ---------------------------------------------------------------

    # HBM ledger: the snapshot's host copies are live bytes this process
    # holds until the (possibly async) write completes — visible on the
    # 'cache' series. Tracked AFTER wait() joins any previous writer
    # (whose finally drops the shared key — tracking earlier would let
    # that drop erase the new entry), released in _write_guarded / save.

    @staticmethod
    def _track_snapshot_ledger(snap):
        try:
            from paddle_tpu.observability import memory as _memory

            if _memory.ENABLED:
                _memory.track("checkpoint_snapshot",
                              sum(a.nbytes for a in snap.values()),
                              "cache")
        except Exception:
            pass

    @staticmethod
    def _drop_snapshot_ledger():
        try:
            from paddle_tpu.observability import memory as _memory

            _memory.drop("checkpoint_snapshot", "cache")
        except Exception:
            pass

    def save(self, step, serial=None, extra=None):
        """Synchronous save: snapshot + write + rename before returning.
        Returns the final checkpoint path. Raises on failure (async saves
        record to ``last_error`` instead)."""
        snap = self._snapshot(self._live_scope())
        rng = self._rng_state()
        self.wait()
        self._track_snapshot_ledger(snap)
        try:
            return self._write(snap, rng, int(step),
                               int(serial if serial is not None else step),
                               extra or {})
        finally:
            self._drop_snapshot_ledger()

    def save_async(self, step, serial=None, extra=None):
        """Snapshot on the calling thread, write on a background one.
        A still-running previous write is joined first (saves are
        ordered; at most one buffered). Returns the serial."""
        snap = self._snapshot(self._live_scope())
        rng = self._rng_state()
        serial = int(serial if serial is not None else step)
        self.wait()
        self._track_snapshot_ledger(snap)
        t = threading.Thread(
            target=self._write_guarded,
            args=(snap, rng, int(step), serial, extra or {}),
            name="paddle-tpu-ckpt-writer", daemon=True)
        self._thread = t
        t.start()
        return serial

    def wait(self):
        """Block until the in-flight async write (if any) finishes."""
        t = self._thread
        if t is not None and t.is_alive():
            t.join()
        self._thread = None

    def _write_guarded(self, snap, rng, step, serial, extra):
        try:
            self._write(snap, rng, step, serial, extra)
        except Exception as exc:  # noqa: BLE001 - async: report, don't kill
            self.last_error = exc
        finally:
            self._drop_snapshot_ledger()

    def _write_one_var(self, tmp_dir, name, arr):
        """Write one var's file(s) into ``tmp_dir``; returns its manifest
        meta. The seam the elastic layer's ShardedCheckpointManager
        overrides to lay a var out as per-shard files instead."""
        fname = _safe_name(name) + ".npy"
        path = os.path.join(tmp_dir, fname)
        np.save(path, arr)
        return {
            "file": fname,
            "sha256": _sha256_file(path),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "bytes": int(arr.nbytes),
        }

    def _write(self, snap, rng, step, serial, extra):
        t0 = time.perf_counter()
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        final_dir = os.path.join(self.checkpoint_dir,
                                 "checkpoint_%d" % serial)
        tmp_dir = "%s.tmp-%d" % (final_dir, os.getpid())
        shutil.rmtree(tmp_dir, ignore_errors=True)
        try:
            os.makedirs(tmp_dir)
            vars_meta = {}
            total_bytes = 0
            chaos_on = chaos.ENABLED
            for name in sorted(snap):
                arr = snap[name]
                vars_meta[name] = self._write_one_var(tmp_dir, name, arr)
                if chaos_on:
                    # the mid-write kill/IO-fault point: var files exist,
                    # no manifest yet — a crash here MUST be invisible to
                    # the next restore
                    chaos.fault("ckpt.write")
                total_bytes += int(arr.nbytes)
            manifest = {
                "manifest_version": MANIFEST_VERSION,
                "serial": serial,
                "step": step,
                "ts": time.time(),
                "vars": vars_meta,
                "rng": rng,
                "extra": extra,
            }
            mpath = os.path.join(tmp_dir, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            # Timed acquire [C003]: _write runs inside the SIGTERM
            # handler chain (TrainSession._signal_handler -> save), and
            # the signal may have interrupted the async writer mid-
            # publish on this very process — an untimed acquire would
            # deadlock short of the final checkpoint. 30s bounds a
            # wedged peer; the raise lands in save()/save_async()'s
            # existing failure accounting and the tmp dir is swept.
            if not self._write_lock.acquire(timeout=30.0):
                raise RuntimeError(
                    "checkpoint publish lock held >30s; aborting save "
                    "of serial %d (peer writer wedged?)" % serial)
            try:
                shutil.rmtree(final_dir, ignore_errors=True)  # re-save
                os.replace(tmp_dir, final_dir)
            finally:
                self._write_lock.release()
            _fsync_dir(self.checkpoint_dir)
        except BaseException:
            _failures.inc(stage="save")
            from paddle_tpu.observability import blackbox

            if blackbox.ENABLED:
                import sys

                exc = sys.exc_info()[1]
                blackbox.record(
                    "checkpoint_failure", stage="save", serial=serial,
                    exc_type=type(exc).__name__,
                    exc_message=str(exc)[:500])
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        self.last_saved_serial = serial
        self._prune(keep_serial=serial)
        dt = time.perf_counter() - t0
        _save_seconds.observe(dt)
        _save_bytes.set(total_bytes)
        from paddle_tpu.observability import blackbox

        if blackbox.ENABLED:
            blackbox.record("checkpoint_saved", serial=serial, step=step,
                            bytes=total_bytes, seconds=round(dt, 4))
        return final_dir

    def _prune(self, keep_serial=None):
        serials = complete_serials(self.checkpoint_dir)
        prune = [s for s in serials
                 if s != keep_serial and s not in self.pinned_serials]
        excess = len(serials) - self.max_to_keep
        for s in prune[:max(excess, 0)]:
            shutil.rmtree(
                os.path.join(self.checkpoint_dir, "checkpoint_%d" % s),
                ignore_errors=True)
        # a writer killed mid-save leaves .tmp dirs; they are dead weight
        # once a NEWER complete checkpoint exists — but another process
        # sharing this dir may be writing its .tmp-<pid> RIGHT NOW, and
        # sweeping a live writer's dir turns its rename into a spurious
        # failure, so only dead writers' leftovers are swept
        try:
            for d in os.listdir(self.checkpoint_dir):
                if ".tmp-" not in d or not d.startswith("checkpoint_"):
                    continue
                base, _, pidstr = d[len("checkpoint_"):].partition(".tmp-")
                if not (base.isdigit() and serials
                        and int(base) <= max(serials)):
                    continue
                if pidstr.isdigit() and int(pidstr) != os.getpid():
                    try:
                        os.kill(int(pidstr), 0)
                        continue  # writer alive: not ours to sweep
                    except ProcessLookupError:
                        pass  # dead writer: orphaned leftovers
                    except OSError:
                        continue  # exists but not ours (EPERM): skip
                shutil.rmtree(
                    os.path.join(self.checkpoint_dir, d),
                    ignore_errors=True)
        except OSError:
            pass

    # -- restore ------------------------------------------------------------

    def _quarantine(self, serial, problems):
        """A corrupt checkpoint is EVIDENCE: rename it out of the serial
        namespace instead of deleting, so restores stop considering it
        but an engineer can still autopsy the bytes."""
        src = os.path.join(self.checkpoint_dir, "checkpoint_%d" % serial)
        n = 0
        dst = "%s.corrupt-%d" % (src, n)
        while os.path.exists(dst):
            n += 1
            dst = "%s.corrupt-%d" % (src, n)
        try:
            os.replace(src, dst)
            # bounded evidence locker: keep the newest few corpses — a
            # storage layer that corrupts every save must not fill the
            # volume with model-sized quarantine dirs (which would then
            # break the healthy save path too)
            corpses = sorted(
                d for d in os.listdir(self.checkpoint_dir)
                if ".corrupt-" in d and d.startswith("checkpoint_"))
            for d in corpses[:-4]:
                shutil.rmtree(os.path.join(self.checkpoint_dir, d),
                              ignore_errors=True)
        except OSError:
            dst = None
        _failures.inc(stage="restore")
        _restores.inc(outcome="corrupt_skipped")
        from paddle_tpu.observability import blackbox

        if blackbox.ENABLED:
            blackbox.record(
                "checkpoint_quarantined", serial=serial,
                quarantined_to=dst, problems=problems[:8])
        import logging

        logging.getLogger("paddle_tpu.resilience.checkpoint").warning(
            "checkpoint serial %d failed verification (%s); quarantined "
            "to %s, falling back to an older serial",
            serial, "; ".join(problems[:3]), dst)
        return dst

    def restore(self, serial=None, restore_rng=True):
        """Load the newest *verified* checkpoint (or exactly ``serial``).
        Corrupt/partial serials are quarantined and skipped serial-by-
        serial. Returns the loaded manifest (with ``serial`` key) or None
        when nothing loadable exists."""
        serials = complete_serials(self.checkpoint_dir)
        if serial is not None:
            serials = [s for s in serials if s == int(serial)]
        for s in reversed(serials):
            step_dir = os.path.join(self.checkpoint_dir,
                                    "checkpoint_%d" % s)
            manifest = read_manifest(step_dir)
            if manifest is not None and not manifest.get("vars"):
                # a v1 marker manifest (io.save_checkpoint): complete,
                # but not this manager's dialect — "restoring" it would
                # load zero vars and still report success. Not corrupt
                # either (io.load_checkpoint loads it), so skip without
                # quarantining.
                continue
            problems = verify_checkpoint_dir(step_dir, manifest)
            if problems:
                self._quarantine(s, problems)
                continue
            try:
                self._load_into_scope(step_dir, manifest)
            except Exception as exc:  # noqa: BLE001 - treat as corrupt
                self._quarantine(s, ["load failed: %s" % exc])
                continue
            if restore_rng:
                self._restore_rng(manifest.get("rng"))
            _restores.inc(outcome="ok")
            return manifest
        return None

    def _load_into_scope(self, step_dir, manifest):
        scope = self._live_scope()
        for name, meta in manifest.get("vars", {}).items():
            scope.set_value(name, assemble_var(step_dir, meta))

    def _restore_rng(self, rng):
        exe = self._executor
        if exe is None or not rng:
            return
        if hasattr(exe, "_base_seed"):
            exe._base_seed = int(rng["base_seed"])
        if hasattr(exe, "_run_counter"):
            exe._run_counter = int(rng["run_counter"])

    def latest_serial(self):
        serials = complete_serials(self.checkpoint_dir)
        return serials[-1] if serials else None
