"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle-Fluid
capabilities.

Design (see SURVEY.md): the user-visible contract is Fluid's declarative
Program/Block/Operator graph built from Python ``layers.*`` calls with
``append_backward`` graph-level autodiff and optimizer *ops* — but the
execution engine is a whole-program XLA compiler: ``Executor(TPUPlace())``
lowers the entire op graph to one JAX function, ``jax.jit``-compiles it once
per (program, feed-shapes, mesh) and caches the executable. Multi-device
training is GSPMD sharding over a ``jax.sharding.Mesh`` (ParallelExecutor),
not per-op kernel dispatch + NCCL as in the CUDA reference.

Reference parity: python/paddle/fluid/__init__.py in reyoung/Paddle.
"""

from paddle_tpu.core.types import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    Place,
    VarType,
    core_version,
)
from paddle_tpu import framework  # noqa: F401
from paddle_tpu import ops as _ops  # noqa: F401  (registers all operators)
from paddle_tpu.framework import (  # noqa: F401
    Program,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    cpu_places,
    tpu_places,
)
from paddle_tpu import initializer  # noqa: F401
from paddle_tpu import layers  # noqa: F401
from paddle_tpu import nets  # noqa: F401
from paddle_tpu import backward  # noqa: F401
from paddle_tpu.backward import append_backward, calc_gradient  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import regularizer  # noqa: F401
from paddle_tpu import clip  # noqa: F401
from paddle_tpu import metrics  # noqa: F401
from paddle_tpu import evaluator  # noqa: F401
from paddle_tpu import recordio_writer  # noqa: F401
from paddle_tpu import profiler  # noqa: F401
from paddle_tpu.executor import Executor, global_scope, scope_guard  # noqa: F401
from paddle_tpu.parallel_executor import (  # noqa: F401
    ParallelExecutor,
    BuildStrategy,
    ExecutionStrategy,
)
from paddle_tpu.data_feeder import DataFeeder  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import inference  # noqa: F401
from paddle_tpu import transpiler  # noqa: F401
from paddle_tpu import flags  # noqa: F401
from paddle_tpu import resilience  # noqa: F401
from paddle_tpu import debugger  # noqa: F401
from paddle_tpu import analysis  # noqa: F401
from paddle_tpu.core import passes  # noqa: F401
from paddle_tpu.transpiler import memory_optimize, release_memory  # noqa: F401
from paddle_tpu.transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from paddle_tpu.core.lod import (  # noqa: F401
    LoDTensor,
    create_lod_tensor,
    create_random_int_lodtensor,
)
from paddle_tpu import average  # noqa: F401
from paddle_tpu.core.selected_rows import SelectedRows  # noqa: F401
from paddle_tpu import unique_name  # noqa: F401
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from paddle_tpu import contrib  # noqa: F401
from paddle_tpu.executor import Scope  # noqa: F401
from paddle_tpu.layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401,E501
from paddle_tpu.layers.control_flow import LoDTensorArray  # noqa: F401
from paddle_tpu import serving  # noqa: F401
from paddle_tpu import elastic  # noqa: F401

__version__ = "0.1.0"

Tensor = LoDTensor
