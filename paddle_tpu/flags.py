"""Global flag system read from FLAGS_* environment variables.

Reference parity: the gflags DEFINE_*/--tryfromenv surface
(``python/paddle/fluid/__init__.py:111-133`` whitelists flags and reads
them from env; C++ point-of-use DEFINE_bool/int in executor.cc, malloc.cc,
gpu_info.cc). Same contract here: ``FLAGS_check_nan_inf=1`` in the
environment flips the flag at import (or via ``refresh_from_env``), and
code reads ``flags.get("check_nan_inf")`` at point of use.
"""

import os

__all__ = ["get", "set_flag", "refresh_from_env", "all_flags"]

# name -> (default, parser)
_DEFS = {
    # numeric guards (operator.cc:754 FLAGS_check_nan_inf)
    "check_nan_inf": (False, bool),
    # per-op sync + memory print (executor.cc FLAGS_benchmark)
    "benchmark": (False, bool),
    # eager GC threshold, GB (executor.cc FLAGS_eager_delete_tensor_gb);
    # device memory is XLA's on TPU — kept for config-surface parity.
    "eager_delete_tensor_gb": (-1.0, float),
    # deterministic reductions (build_strategy.h FLAGS_cpu_deterministic)
    "cpu_deterministic": (False, bool),
    # poison freshly allocated host buffers (malloc.cc FLAGS_init_allocated_mem)
    "init_allocated_mem": (False, bool),
    # fraction of device memory to use (gpu_info.cc:22) — advisory on TPU
    # (maps to XLA_PYTHON_CLIENT_MEM_FRACTION at process start).
    "fraction_of_gpu_memory_to_use": (0.92, float),
    # reader queue soak-test mode (FLAGS_reader_queue_speed_test_mode)
    "reader_queue_speed_test_mode": (False, bool),
    # rpc knobs kept for config parity (rpc_deadline etc.)
    "rpc_deadline": (180000, int),
    # forced rematerialization for all grad ops (memory_optimize's lever)
    "remat_gradients": (False, bool),
    # route dynamic_lstm through the fused Pallas recurrence kernel
    # (kernels/lstm_cell.py); opt-in until measured on hardware
    "use_pallas_lstm": (False, bool),
    # same for dynamic_gru (kernels/gru_cell.py)
    "use_pallas_gru": (False, bool),
    # lower conv2d internally in NHWC (transpose sandwich; adjacent
    # sandwiches cancel under XLA) — the layout experiment for the MFU
    # push; numerics identical, measured per-hardware
    "conv_nhwc": (False, bool),
    # override scaled_dot_product_attention's impl="auto" resolution:
    # "auto" (backend picks), "pallas" (force flash kernel), "reference"
    # (XLA-composed attention) — the escape hatch when the Pallas compile
    # path is unavailable/slow on a given rig
    "attention_impl": ("auto", str),
    # ragged paged-attention decode (kernels/paged_attention.py) impl
    # resolution for paged_attention's impl="auto": "auto" (Pallas kernel
    # on TPU targets, composed gather+softmax reference on CPU), "pallas"
    # (force the kernel — interpret mode on CPU, the test path),
    # "reference" (force the composed path everywhere)
    "paged_attention": ("auto", str),
    # beam-decode hypothesis reorder over the paged slot pool
    # (serving/generation.py SlotDecodeSession(beam_width=K)):
    # "rebind" (default) executes the per-step parent permutation as
    # page-table row rebinds + host refcount moves — a pure permutation
    # copies ZERO KV bytes; "reference" is the in-tree copy-reorder
    # oracle (every surviving hypothesis physically copies its parent's
    # resident pages, the pre-paged-attention baseline) — bit-identical
    # tokens, O(T) bytes per reorder, the A/B bench.py's beam_speedup
    # gates. The oracle needs ~beam_width * pages_per_slot free-page
    # headroom for its transient copies; size num_pages accordingly.
    "beam_reorder": ("rebind", str),
    # backward pass of the flash kernel: "pallas" (FlashAttention-2-style
    # dkv/dq kernels, O(block) memory) or "reference" (recompute through
    # the XLA-composed path — materializes the [T, S] score matrix)
    "flash_backward": ("pallas", str),
    # persistent executable cache root (core/exec_cache.py): XLA compile
    # cache + AOT executable images live under it, shared across
    # processes; empty disables persistence (in-memory caching stays on)
    "exec_cache_dir": ("", str),
    # TOTAL byte budget for the persistent cache dir (-1 = unbounded),
    # split evenly: LRU eviction on the XLA layer, oldest-first trim on
    # the AOT image layer
    "exec_cache_max_bytes": (-1, int),
    # step telemetry (observability/telemetry.py): per-step wall time,
    # feed/fetch bytes, transfer seconds, device memory and MFU recorded
    # by every executor run; off = zero hot-path overhead (module bool)
    "telemetry": (False, bool),
    # where the Prometheus scrape + step JSONL land at exit / flush():
    # <path> gets the text-format metrics, <path>.steps.jsonl the per-step
    # records; empty disables the files (in-memory registry stays live)
    "metrics_path": ("", str),
    # MFU accounting override, TFLOP/s: 0 = auto from device_kind (the
    # chip table); set explicitly on hardware the table doesn't know
    # (or to make CPU-proxy MFU numbers comparable run-to-run)
    "peak_tflops": (0.0, float),
    # run the structural program verifier (analysis/verify.py) before
    # every fresh compile in Executor.run/run_multi_step, at Predictor
    # load, and after every transpiler: malformed graphs fail with
    # structured diagnostics instead of XLA tracebacks. Opt-in — the
    # verifier walk is O(ops) per fresh compile, never per step.
    "verify_program": (False, bool),
    # crash black box (observability/blackbox.py): the post-mortem role of
    # the reference's FLAGS_call_stack_level + glog FATAL dumps — a JSON
    # file of the recent flight events (dispatches, recompiles, exceptions,
    # flag snapshot) written on unhandled executor/Predictor exceptions,
    # fatal signals (SIGTERM/SIGABRT), the watchdog, or blackbox.dump();
    # empty disables the recorder (zero hot-path overhead)
    "blackbox_path": ("", str),
    # hang watchdog (observability/watchdog.py): start the background
    # progress monitor at import — the ExceptionHolder-promptness role
    # (framework/details/exception_holder.h) for hangs XLA never surfaces
    # (a stuck collective, a wedged fetch). Opt-in; watchdog.start() is
    # the programmatic switch.
    "watchdog": (False, bool),
    # seconds without executor/fetch progress before the watchdog declares
    # a hang (dumps thread stacks + black box); 0 = auto — a multiple of
    # telemetry's p95 step time when available, else 300s
    "watchdog_timeout": (0.0, float),
    # after a declared hang: dump, then abort the process (os.abort) so a
    # supervisor restarts it instead of burning TPU-hours wedged — the
    # fail-fast discipline of the reference's PADDLE_ENFORCE FATALs
    "watchdog_abort": (False, bool),
    # NaN provenance (observability/nan_provenance.py): when the
    # FLAGS_check_nan_inf on-device scan trips, replay the step per-op
    # from a pre-step state snapshot and blame the FIRST op whose output
    # is non-finite (operator.cc:754's per-op check, paid only after a
    # trip instead of every step). Costs one device-side copy of the
    # mutable state per step while check_nan_inf is on.
    "nan_provenance": (True, bool),
    # periodic checkpointing cadence for resilience.TrainSession, in
    # steps (reference: io.py CheckpointConfig.save_interval_secs role,
    # step-keyed here because TPU steps are the natural clock); 0 = only
    # explicit/final/signal checkpoints
    "checkpoint_interval_steps": (0, int),
    # same cadence on a wall-clock basis, seconds; whichever of the two
    # intervals fires first wins, 0 disables this one
    "checkpoint_interval_secs": (0.0, float),
    # checkpoint retention for resilience.CheckpointManager (reference:
    # CheckpointConfig.max_num_checkpoints); older complete serials
    # beyond this count are pruned after each successful save
    "checkpoint_max_to_keep": (3, int),
    # classified-transient retry budget (resilience/retry.py) applied to
    # the executor fresh-compile/dispatch paths — the listen_and_serv/
    # grpc retry discipline the reference buries in brpc channel
    # options; 0 disables dispatch retrying (zero hot-path overhead
    # beyond one flag read). MasterClient's reconnect-and-retry-once
    # across a master restart is fixed, not governed by this flag.
    "dispatch_retries": (0, int),
    # base of the exponential backoff between retries, seconds (each
    # attempt waits base * 2^attempt plus up to 50% jitter)
    "retry_backoff_s": (0.05, float),
    # deterministic fault injection (resilience/chaos.py): a spec like
    # "seed=7;kill@step=12;io@site=ckpt.write,p=0.5" arms seeded
    # kill-points and injected IO/compile/slow faults at named sites —
    # the chaos-monkey harness the crash/resume CI stage drives; empty
    # disables (module-bool guard, zero overhead)
    "chaos_spec": ("", str),
    # speculative decoding over the paged slot pool
    # (serving/generation.py SlotDecodeSession(speculative=...)): "on"
    # (default) runs the draft/verify tree dispatch when the session was
    # built speculative; "off" is the bit-exactness oracle — the session
    # falls back to the plain one-token step program and the accepted
    # token streams of the two modes must be BIT-identical (greedy exact,
    # sampled via the (seed, slot, position) key scheme). Read at every
    # step, so tests can flip it mid-session without rebuilding.
    "speculative": ("on", str),
    # tree-attention verify kernel (kernels/paged_attention.py
    # paged_tree_attention) impl resolution for impl="auto": "auto"
    # (Pallas kernel on TPU targets, composed gather+ancestor-mask
    # reference on CPU), "pallas" (force the kernel — interpret mode on
    # CPU, the test path), "reference" (force the composed path)
    "tree_attention": ("auto", str),
    # route the transformer's label-smoothed CE head through the fused
    # single-pass op (ops/loss_ops.py fused_label_smooth_ce): bf16
    # logits with f32-accumulated reductions, hand-written one-pass
    # backward. MFU lever #1 from docs/MFU_PLAN.md (the composed head
    # moves ~10 GB/step of f32 logits-shaped traffic at bench shapes);
    # opt-in until the chip A/B (watcher leg transformer-ce-fused) lands
    "fused_ce": (False, bool),
    # request-scoped distributed tracing across the serving plane
    # (observability/tracing.py): ServingClient mints a trace id that
    # rides the JSON-lines envelope; frontend + decode session record
    # per-request span waterfalls (queue/admit/prefill/dispatch/flush)
    # into a bounded ring, exported as <metrics_path>.traces.jsonl and
    # rendered by tools/trace_view.py. Module-bool guard, same contract
    # as FLAGS_telemetry: off = zero per-request allocations, zero wire
    # bytes, zero fresh-compile delta
    "request_tracing": (False, bool),
    # runtime lock witness (observability/lock_witness.py): named-lock
    # registration wrappers around every framework lock record per-thread
    # acquisition-order edges into a global graph, flag lock-order cycles
    # (potential deadlock) and holds spanning a device dispatch, and
    # annotate blackbox/watchdog thread dumps with which named locks each
    # thread holds. Module-bool guard read at lock CONSTRUCTION time: off
    # (default) means every factory returns a plain threading primitive —
    # zero wrapper allocations, zero per-acquire overhead. Arm via the
    # environment (FLAGS_lock_witness=1) before import, or
    # lock_witness.enable() before the subsystems under test build.
    "lock_witness": (False, bool),
    # training-step observatory (observability/step_profiler.py):
    # phase-attributed per-step records (input wait / feed / compile /
    # dispatch / device / fetch) for Executor.run / run_multi_step /
    # ParallelExecutor, with achieved-FLOP/s and achieved-MFU joined from
    # the hlo_cost_model fused-group table, an online median+MAD step-time
    # regression detector that names the guilty phase, and a JSONL export
    # (<metrics_path>.stepprof.jsonl) the perf ledger ingests. Module-bool
    # guard, same contract as FLAGS_telemetry: off = one attribute read
    # per step, zero allocations, zero fresh-compile delta.
    "step_profile": (False, bool),
}


def _parse(raw, parser):
    if parser is bool:
        return str(raw).lower() in ("1", "true", "yes", "on")
    return parser(raw)


_values = {}


def refresh_from_env():
    """Re-read every FLAGS_<name> env var (init_gflags --tryfromenv)."""
    for name, (default, parser) in _DEFS.items():
        raw = os.environ.get("FLAGS_" + name)
        _values[name] = _parse(raw, parser) if raw is not None else default


def get(name):
    if name not in _DEFS:
        raise KeyError("unknown flag %r (known: %s)"
                       % (name, sorted(_DEFS)))
    return _values[name]


def set_flag(name, value):
    if name not in _DEFS:
        raise KeyError("unknown flag %r" % name)
    _values[name] = _parse(value, _DEFS[name][1])


def all_flags():
    return dict(_values)


refresh_from_env()
