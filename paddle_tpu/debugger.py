"""Program inspection: pretty-printer + graphviz export.

Reference parity: ``python/paddle/fluid/debugger.py`` (pprint_program_codes,
draw_block_graphviz) and ``framework/ir/graph_viz_pass.cc`` (dot output of
the op graph).
"""

__all__ = ["program_to_code", "draw_block_graphviz", "dump_sharding_plan"]


def _fmt_var(v):
    from paddle_tpu.framework import Parameter

    kind = "param" if isinstance(v, Parameter) else (
        "data" if getattr(v, "is_data", False) else "var"
    )
    extras = []
    if v.persistable:
        extras.append("persist")
    if v.stop_gradient:
        extras.append("stop_grad")
    return "%s %s : %s%s %s" % (
        kind, v.name, v.dtype,
        list(v.shape) if v.shape is not None else "?",
        ",".join(extras),
    )


def program_to_code(program, skip_op_callstack=True):
    """Readable text dump of every block (debugger.pprint_program_codes)."""
    lines = []
    for block in program.blocks:
        lines.append(
            "-- block %d (parent %d) --" % (block.idx, block.parent_idx)
        )
        for name in sorted(block.vars):
            lines.append("  " + _fmt_var(block.vars[name]))
        for i, op in enumerate(block.ops):
            ins = ", ".join(
                "%s=[%s]" % (slot, ",".join(ns))
                for slot, ns in sorted(op.inputs.items()) if ns
            )
            outs = ", ".join(
                "%s=[%s]" % (slot, ",".join(ns))
                for slot, ns in sorted(op.outputs.items()) if ns
            )
            attrs = ", ".join(
                "%s=%r" % (k, v)
                for k, v in sorted(op.attrs.items())
                if not k.startswith("__") and k not in ("op_role",
                                                        "op_role_var")
            )
            lines.append(
                "  [%3d] %s(%s) -> %s  {%s}" % (i, op.type, ins, outs,
                                                attrs)
            )
    return "\n".join(lines)


def draw_block_graphviz(block, highlights=None, path="/tmp/program.dot"):
    """Emit a graphviz dot file of a block's op/var dataflow
    (graph_viz_pass.cc / debugger.draw_block_graphviz parity)."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    var_nodes = set()

    def var_node(name):
        nid = "var_" + name.replace(".", "_").replace("@", "_").replace(
            "/", "_"
        )
        if name not in var_nodes:
            var_nodes.add(name)
            color = ', style=filled, fillcolor="#ffd2d2"' if (
                name in highlights
            ) else ""
            lines.append(
                '  %s [label="%s", shape=oval%s];' % (nid, name, color)
            )
        return nid

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append(
            '  %s [label="%s", shape=box, style=filled, '
            'fillcolor="#d2e3fc"];' % (op_id, op.type)
        )
        for name in op.input_arg_names():
            if name:
                lines.append("  %s -> %s;" % (var_node(name), op_id))
        for name in op.output_arg_names():
            if name:
                lines.append("  %s -> %s;" % (op_id, var_node(name)))
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot


def dump_sharding_plan(policy, file=None):
    """Print a ShardingPolicy's var->PartitionSpec plan (parallel/mesh.py),
    flagging vars that fell back to replication ("no silent caps")."""
    import sys

    out = file or sys.stdout
    print("sharding plan (mesh=%s, strategy=%s):"
          % (dict(policy.mesh.shape), policy.strategy), file=out)
    for name, (spec, note) in policy.plan().items():
        print("  %-40s %s%s" % (name, spec, "  [" + note + "]" if note
                                else ""), file=out)
