"""Program inspection: pretty-printer + graphviz export.

Reference parity: ``python/paddle/fluid/debugger.py`` (pprint_program_codes,
draw_block_graphviz) and ``framework/ir/graph_viz_pass.cc`` (dot output of
the op graph).
"""

__all__ = ["program_to_code", "draw_block_graphviz", "dump_sharding_plan"]


def _spec_label(v):
    """'P(fsdp, tp)'-style label for a var the sharding transpiler
    annotated (parallel/sharding.py stamps ``partition_spec`` /
    ``reshard_spec``); None when unannotated."""
    spec = getattr(v, "partition_spec", None)
    reshard = getattr(v, "reshard_spec", None)
    if spec is None and reshard is None:
        return None
    from paddle_tpu.parallel.sharding import _spec_str

    parts = []
    if spec is not None:
        parts.append(_spec_str(spec))
    if reshard is not None:
        parts.append("reshard->%s" % _spec_str(reshard))
    return " ".join(parts)


def _fmt_var(v):
    from paddle_tpu.framework import Parameter

    kind = "param" if isinstance(v, Parameter) else (
        "data" if getattr(v, "is_data", False) else "var"
    )
    extras = []
    if v.persistable:
        extras.append("persist")
    if v.stop_gradient:
        extras.append("stop_grad")
    spec = _spec_label(v)
    return "%s %s : %s%s %s%s" % (
        kind, v.name, v.dtype,
        list(v.shape) if v.shape is not None else "?",
        ",".join(extras),
        "  @" + spec if spec else "",
    )


def _diag_index(diagnostics):
    """(block_idx, op_idx) -> [Diagnostic], plus flagged var names."""
    by_op = {}
    var_names = set()
    for d in diagnostics or ():
        if d.block_idx is not None and d.op_idx is not None:
            by_op.setdefault((d.block_idx, d.op_idx), []).append(d)
        var_names.update(d.var_names)
    return by_op, var_names


def program_to_code(program, skip_op_callstack=True, diagnostics=None):
    """Readable text dump of every block (debugger.pprint_program_codes),
    op attrs included. With ``diagnostics`` (from ``Program.verify`` /
    ``analysis.lint``), flagged ops get a ``!`` prefix and a trailing
    ``!rule`` marker so a dump shows at a glance where the graph is
    broken."""
    by_op, _flagged_vars = _diag_index(diagnostics)
    lines = []
    for block in program.blocks:
        lines.append(
            "-- block %d (parent %d) --" % (block.idx, block.parent_idx)
        )
        for name in sorted(block.vars):
            lines.append("  " + _fmt_var(block.vars[name]))
        for i, op in enumerate(block.ops):
            ins = ", ".join(
                "%s=[%s]" % (slot, ",".join(ns))
                for slot, ns in sorted(op.inputs.items()) if ns
            )
            outs = ", ".join(
                "%s=[%s]" % (slot, ",".join(ns))
                for slot, ns in sorted(op.outputs.items()) if ns
            )
            attrs = ", ".join(
                "%s=%r" % (k, v)
                for k, v in sorted(op.attrs.items())
                if not k.startswith("__") and k not in ("op_role",
                                                        "op_role_var")
            )
            flags_here = by_op.get((block.idx, i), ())
            mark = "!" if flags_here else " "
            line = " %s[%3d] %s(%s) -> %s  {%s}" % (mark, i, op.type, ins,
                                                    outs, attrs)
            if flags_here:
                line += "  !%s" % ",".join(
                    sorted({d.rule for d in flags_here}))
            lines.append(line)
    return "\n".join(lines)


def draw_block_graphviz(block, highlights=None, path="/tmp/program.dot",
                        diagnostics=None):
    """Emit a graphviz dot file of a block's op/var dataflow
    (graph_viz_pass.cc / debugger.draw_block_graphviz parity). Ops and
    vars named by ``diagnostics`` render red, labeled with the rule ids."""
    by_op, flagged_vars = _diag_index(diagnostics)
    highlights = set(highlights or ()) | flagged_vars
    lines = ["digraph G {", "  rankdir=TB;"]
    var_nodes = set()

    def var_node(name):
        nid = "var_" + name.replace(".", "_").replace("@", "_").replace(
            "/", "_"
        )
        if name not in var_nodes:
            var_nodes.add(name)
            color = ', style=filled, fillcolor="#ffd2d2"' if (
                name in highlights
            ) else ""
            v = block._find_var_recursive(name)
            spec = _spec_label(v) if v is not None else None
            label = "%s\\n%s" % (name, spec) if spec else name
            lines.append(
                '  %s [label="%s", shape=oval%s];' % (nid, label, color)
            )
        return nid

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        flags_here = by_op.get((block.idx, i), ())
        if flags_here:
            label = "%s\\n%s" % (op.type, ",".join(
                sorted({d.rule for d in flags_here})))
            fill, border = "#ff9d9d", ', color="#b00020"'
        else:
            label, fill, border = op.type, "#d2e3fc", ""
        lines.append(
            '  %s [label="%s", shape=box, style=filled, '
            'fillcolor="%s"%s];' % (op_id, label, fill, border)
        )
        for name in op.input_arg_names():
            if name:
                lines.append("  %s -> %s;" % (var_node(name), op_id))
        for name in op.output_arg_names():
            if name:
                lines.append("  %s -> %s;" % (op_id, var_node(name)))
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot


def dump_sharding_plan(policy, file=None):
    """Print a sharding plan's var->PartitionSpec table, flagging vars
    that fell back to replication ("no silent caps"). Accepts a
    ShardingPolicy / DerivedShardingPolicy (parallel) or a raw derived
    :class:`parallel.sharding.ShardingPlan`."""
    import sys

    from paddle_tpu.parallel.sharding import ShardingPlan, _spec_str

    out = file or sys.stdout
    if isinstance(policy, ShardingPlan):
        print("derived sharding plan (mesh=%s):" % (policy.mesh_axes,),
              file=out)
        for name in sorted(policy.specs):
            note = policy.notes.get(name, "")
            print("  %-40s %s%s" % (name, _spec_str(policy.specs[name]),
                                    "  [" + note + "]" if note else ""),
                  file=out)
        for r in policy.reshard_points:
            print("  reshard %-32s at op %s (%s) -> %s"
                  % (r["var"], r["op_idx"], r["op_type"], r["spec"]),
                  file=out)
        return
    print("sharding plan (mesh=%s, strategy=%s):"
          % (dict(policy.mesh.shape), policy.strategy), file=out)
    for name, (spec, note) in policy.plan().items():
        print("  %-40s %s%s" % (name, spec, "  [" + note + "]" if note
                                else ""), file=out)
