"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipelined trainer (it predates pipeline parallelism);
this module is the TPU-native design that provides the capability, sized
to the mesh's reserved "pipe" axis (parallel/mesh.py):

* stage s of the network lives on device s of the axis — stage parameters
  are STACKED on a leading dim and sharded over the axis, so each device
  holds only its own stage's weights;
* M microbatches flow through S stages in M + S - 1 ticks; at every tick
  each device runs its stage on the activation it holds, then hands the
  result to the next device with one ``jax.lax.ppermute`` hop (nearest
  neighbor on ICI — the cheapest collective on TPU);
* the schedule is a ``lax.scan`` over ticks, so it is a single compiled
  loop, and because it is built from transposable primitives the BACKWARD
  pipeline comes for free from jax.grad (reverse ppermute direction,
  reverse tick order — exactly GPipe's B-phase).

Activations are fed replicated by default, or batch-sharded over a second
mesh axis (``batch_axis``, pipeline x data parallel); outputs are
stage-stacked. Per-device activation memory is O(local batch), parameter
memory O(params / S). This is the capability layer (like ring_attention):
models wire it explicitly; the Program-level front-end keeps dp/tp/ZeRO
shardings via ParallelExecutor.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import _compat


def stack_stage_params(stage_params_list):
    """[pytree per stage] -> one pytree with a leading stage dim (what
    ``gpipe`` expects; shard dim 0 over the pipe axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *stage_params_list
    )


def _gpipe_shard(params, x, stage_fn, axis_name):
    """Per-device body. params leaves: [1, ...] (this stage's block);
    x: [M, B_local, ...] microbatches (the full batch when replicated, a
    batch shard under gpipe's batch_axis). Returns [M, B_local, ...] —
    only the LAST device's block holds the pipeline output; gpipe()
    slices it out of the stage-stacked global result."""
    n = jax.lax.psum(1, axis_name)
    d = jax.lax.axis_index(axis_name)
    local = jax.tree_util.tree_map(lambda l: l[0], params)
    m = x.shape[0]
    ticks = m + n - 1
    fwd_perm = [(i, i + 1) for i in range(n - 1)]

    # varying-marked zero activation: used for carries and as the cond
    # bubble branch, whose output type must match stage_fn's (varying)
    zero_act = _compat.vary(jnp.zeros_like(x[0]), axis_name)

    def tick(carry, t):
        prev_out, outbuf = carry
        # activation arriving this tick: device 0 injects a fresh
        # microbatch, everyone else receives the left neighbor's output
        recv = jax.lax.ppermute(prev_out, axis_name, fwd_perm)
        inj = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        my_in = jnp.where(d == 0, inj, recv)
        # device d works on microbatch t - d; outside [0, M) the lane is
        # a pipeline bubble — lax.cond SKIPS the stage there, so bubbles
        # cost nothing and stage_fns that are non-finite at zero (log,
        # rsqrt, ...) can't poison values OR gradients
        mb = t - d
        valid = (mb >= 0) & (mb < m)
        my_in = jnp.where(valid, my_in, zero_act)
        y = jax.lax.cond(
            valid,
            lambda a: stage_fn(local, a),
            lambda a: zero_act,
            my_in,
        )
        # the last device banks its (valid) results into the out buffer
        slot = jnp.clip(mb, 0, m - 1)
        cur = jax.lax.dynamic_index_in_dim(outbuf, slot, 0, keepdims=False)
        banked = jnp.where((d == n - 1) & valid, y, cur)
        outbuf = jax.lax.dynamic_update_index_in_dim(outbuf, banked, slot, 0)
        return (y, outbuf), None

    outbuf0 = _compat.vary(jnp.zeros_like(x), axis_name)
    (_, outbuf), _ = jax.lax.scan(
        tick, (zero_act, outbuf0), jnp.arange(ticks)
    )
    return outbuf


def gpipe(stage_fn, stage_params, x, mesh, axis_name="pipe",
          batch_axis=None, param_specs=None):
    """Run x through S pipelined stages.

    Args:
      stage_fn: (params_for_one_stage, activation [B, ...]) -> [B, ...].
        Every stage must map activations to the SAME shape (classic GPipe
        requirement; wrap reshape stages into neighbors).
      stage_params: pytree whose leaves are stage-stacked [S, ...]
        (see stack_stage_params); S must equal mesh.shape[axis_name].
      x: [M, B, ...] — M microbatches.
      mesh: jax.sharding.Mesh containing ``axis_name``.
      batch_axis: optional second mesh axis to keep the microbatch batch
        dim sharded over (pipeline x data parallel on a 2-D mesh). Without
        it the activations are replicated across the other axes.
      param_specs: optional pytree of PartitionSpec matching stage_params,
        for sharding stage weights over FURTHER mesh axes (tensor
        parallelism inside a stage — dp x tp x pp on a 3-D mesh). Every
        spec's dim 0 must be ``axis_name``; inside ``stage_fn`` the
        model-axis collectives (e.g. ``jax.lax.psum(.., "model")`` after
        a row-parallel matmul) are explicit, shard_map-style.

    Returns [M, B, ...]: the pipeline output, differentiable w.r.t. both
    stage_params and x; with batch_axis it stays batch-sharded.
    """
    n = mesh.shape[axis_name]
    leaves = jax.tree_util.tree_leaves(stage_params)
    if not leaves:
        raise ValueError("gpipe: empty stage_params")
    for l in leaves:
        if l.ndim == 0 or l.shape[0] != n:
            raise ValueError(
                "gpipe: every stage_params leaf needs a leading stage dim "
                "equal to the pipe axis size %d, got shape %s (one stage "
                "per device; stack with stack_stage_params, fold deeper "
                "networks into stage_fn)" % (n, l.shape))
    shard_map = _compat.shard_map()
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stage_params
        )
    else:
        for spec in jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda s: isinstance(s, P)):
            # dim-0 entries may be a bare axis name or an axis tuple
            # (P(("pipe", "data"), ...)); require pipe among them
            first = spec[0] if spec else None
            axes0 = first if isinstance(first, tuple) else (first,)
            if axis_name not in axes0:
                raise ValueError(
                    "gpipe: every param_specs entry must shard dim 0 over "
                    "the pipe axis %r, got %s" % (axis_name, spec))
    if batch_axis is not None:
        if batch_axis not in mesh.shape or batch_axis == axis_name:
            raise ValueError(
                "gpipe: batch_axis must name a mesh axis distinct from "
                "the pipe axis %r; got %r (mesh axes: %s)"
                % (axis_name, batch_axis, tuple(mesh.shape)))
        x_spec = P(None, batch_axis)
        out_spec = P(axis_name, batch_axis)
    else:
        x_spec = P()
        out_spec = P(axis_name)
    fn = shard_map(
        functools.partial(
            _gpipe_shard, stage_fn=stage_fn, axis_name=axis_name
        ),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_spec,
    )
    from paddle_tpu.observability import telemetry as _telemetry

    if _telemetry.ENABLED:
        # bubble fraction of this schedule: M useful ticks of M+S-1
        _telemetry.record_pipeline_occupancy(n, x.shape[0])
    stacked = fn(stage_params, x)  # [S*M, B, ...], last block is real
    m = x.shape[0]
    return stacked[(n - 1) * m:]
