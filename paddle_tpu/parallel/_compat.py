"""JAX API compatibility shims shared by the parallel modules.

The shard_map entry point and the varying-axis cast have moved across JAX
releases; both ring_attention and pipeline need the same fallbacks, so
they live here once.
"""

import jax


def shard_map():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map as sm

    return sm


def vary(x, axis_name):
    """Mark a device-uniform value as varying over ``axis_name`` (required
    for scan carries inside shard_map whose outputs become varying)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, (axis_name,))
    return x
