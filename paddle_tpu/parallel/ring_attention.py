"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference framework predates long-context training entirely
(SURVEY.md §5.7 — no attention kernel, no sequence parallelism); this
module is the TPU-native design that provides it:

* ``ring_attention`` — sequence-sharded Q/K/V; K/V blocks rotate around
  the mesh axis with ``jax.lax.ppermute`` (ICI neighbor exchange) while a
  running online-softmax accumulator absorbs one block per step. Memory per
  chip is O(T/N), enabling contexts N× longer than one chip could hold.
* ``ulysses_attention`` — all-to-all re-partition: trade the sequence
  sharding for a head sharding (`jax.lax.all_to_all`), run ordinary
  (flash) attention on full sequences for a head subset, and trade back.
  Cheaper for moderate T when heads % N == 0.

Both are pure per-shard functions for use under ``shard_map`` over a
``jax.sharding.Mesh`` axis, and both are reverse-differentiable (scan +
ppermute / all_to_all have transposition rules), so they drop into the
training path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import _compat

_NEG_INF = -1e30
_shard_map = _compat.shard_map


def _ring_attention_shard(q, k, v, axis_name, causal, sm_scale):
    """Per-shard body. q,k,v: [B, H, Tl, d] local sequence chunks."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    Tl = q.shape[2]
    d = q.shape[3]
    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my * Tl + jnp.arange(Tl)  # global query positions

    def _vary(x):
        # Mark device-uniform initial carries as varying over the ring axis
        # (shard_map's varying-axis type system requires carry in/out match).
        return _compat.vary(x, axis_name)

    acc0 = _vary(jnp.zeros(q.shape[:3] + (d,), jnp.float32))
    m0 = _vary(jnp.full(q.shape[:3] + (1,), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros(q.shape[:3] + (1,), jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        acc, m, l, k_cur, v_cur = carry
        src = (my - i) % n  # owner of the block currently held
        s = jnp.einsum(
            "bhtd,bhsd->bhts", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            s = jnp.where(
                k_pos[None, None, None, :] <= q_pos[None, None, :, None],
                s,
                _NEG_INF,
            )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bhsd->bhtd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc_new, m_new, l_new, k_next, v_next), None

    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="data", causal=False,
                   sm_scale=None):
    """Ring attention over sequence-sharded [B, H, T, d] tensors.

    q/k/v are GLOBAL arrays; the mesh axis ``axis_name`` shards the
    sequence (dim 2). Returns the global output with the same sharding.
    """
    shard_map = _shard_map()

    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_shard,
            axis_name=axis_name,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def _ulysses_shard(q, k, v, axis_name, causal, sm_scale):
    """Per-shard body. q,k,v: [B, H, Tl, d]; requires H % n == 0."""
    # public entry: Pallas flash kernel on TPU targets, XLA reference on
    # CPU (pallas_call composes with shard_map)
    from paddle_tpu.kernels.flash_attention import flash_attention

    # [B, H, Tl, d] -> all_to_all -> [B, H/n, T, d]
    def seq_to_head(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def head_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh = seq_to_head(q)
    kh = seq_to_head(k)
    vh = seq_to_head(v)
    out = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis_name="data", causal=False,
                      sm_scale=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention."""
    shard_map = _shard_map()

    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError(
            "ulysses_attention needs heads (%d) divisible by axis size (%d)"
            % (q.shape[1], n)
        )
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ulysses_shard,
            axis_name=axis_name,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
