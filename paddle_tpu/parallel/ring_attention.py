"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference framework predates long-context training entirely
(SURVEY.md §5.7 — no attention kernel, no sequence parallelism); this
module is the TPU-native design that provides it:

* ``ring_attention`` — sequence-sharded Q/K/V; K/V blocks rotate around
  the mesh axis with ``jax.lax.ppermute`` (ICI neighbor exchange) while a
  running online-softmax accumulator absorbs one block per step. Memory per
  chip is O(T/N), enabling contexts N× longer than one chip could hold.
* ``ulysses_attention`` — all-to-all re-partition: trade the sequence
  sharding for a head sharding (`jax.lax.all_to_all`), run ordinary
  (flash) attention on full sequences for a head subset, and trade back.
  Cheaper for moderate T when heads % N == 0.

Both are pure per-shard functions for use under ``shard_map`` over a
``jax.sharding.Mesh`` axis, and both are reverse-differentiable (scan +
ppermute / all_to_all have transposition rules), so they drop into the
training path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import _compat

_NEG_INF = -1e30
_shard_map = _compat.shard_map


def _ring_attention_shard(q, k, v, axis_name, causal, sm_scale):
    """Per-shard body. q,k,v: [B, H, Tl, d] local sequence chunks."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    Tl = q.shape[2]
    d = q.shape[3]
    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my * Tl + jnp.arange(Tl)  # global query positions

    def _vary(x):
        # Mark device-uniform initial carries as varying over the ring axis
        # (shard_map's varying-axis type system requires carry in/out match).
        return _compat.vary(x, axis_name)

    acc0 = _vary(jnp.zeros(q.shape[:3] + (d,), jnp.float32))
    m0 = _vary(jnp.full(q.shape[:3] + (1,), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros(q.shape[:3] + (1,), jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        acc, m, l, k_cur, v_cur = carry
        src = (my - i) % n  # owner of the block currently held
        s = jnp.einsum(
            "bhtd,bhsd->bhts", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            s = jnp.where(
                k_pos[None, None, None, :] <= q_pos[None, None, :, None],
                s,
                _NEG_INF,
            )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bhsd->bhtd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc_new, m_new, l_new, k_next, v_next), None

    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _ring_flash_shard(q, k, v, axis_name, causal, sm_scale):
    """Ring attention with the Pallas flash kernel as the per-block
    engine: each rotating K/V block is absorbed through
    ``_flash_forward`` (O(block) memory — no [Tl, Tl] score matrix even
    within a shard) and the per-block (out, lse) partials merge by
    log-sum-exp. The causal diagonal block is PEELED before the scan so
    the kernel's static ``causal`` flag applies only there; rotated
    blocks are whole-block keep/drop decided by a traced ownership test.

    Backward recomputes through the XLA reference shard
    (``_ring_attention_shard``) under custom_vjp at the ring level —
    the same recompute strategy flash attention itself launched with.
    """
    from paddle_tpu.kernels.flash_attention import (
        _DEFAULT_BLOCK_K,
        _DEFAULT_BLOCK_Q,
        _flash_forward,
        _is_tpu_target,
    )

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    interpret = not _is_tpu_target()
    perm = [(j, (j + 1) % n) for j in range(n)]

    def block_partial(k_blk, v_blk, blk_causal):
        out, lse = _flash_forward(
            q, k_blk, v_blk, None, blk_causal, sm_scale,
            _DEFAULT_BLOCK_Q, _DEFAULT_BLOCK_K, interpret,
        )
        # lse: [B, H, 1, Tp] (padded); out: [B, H, Tl, d]
        Tl = q.shape[2]
        return out.astype(jnp.float32), jnp.moveaxis(
            lse[:, :, :, :Tl], 3, 2)  # -> [B, H, Tl, 1]

    def merge(acc, lse_acc, out_b, lse_b, keep):
        # drop the whole block by sending its lse to -inf
        lse_b = jnp.where(keep, lse_b, _NEG_INF)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_b = jnp.exp(lse_b - lse_new)
        return acc * w_acc + out_b * w_b, lse_new

    # Peeled diagonal block: own K/V, causal iff the global op is causal.
    acc, lse_acc = block_partial(k, v, causal)
    # First rotation happens alongside the peeled compute above.
    k_cur = jax.lax.ppermute(k, axis_name, perm)
    v_cur = jax.lax.ppermute(v, axis_name, perm)

    def step(carry, i):
        acc, lse_acc, k_cur, v_cur = carry
        # Compute on the HELD block while the next exchange is in
        # flight — both read k_cur, so XLA overlaps ICI with the MXU
        # (the reference shard's schedule).
        out_b, lse_b = block_partial(k_cur, v_cur, False)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my - i) % n  # owner of the held block
        # causal: keep only blocks strictly before this shard's queries
        keep = (src < my) if causal else jnp.asarray(True)
        acc, lse_acc = merge(acc, lse_acc, out_b, lse_b, keep)
        return (acc, lse_acc, k_next, v_next), None

    if n > 1:
        (acc, lse_acc, _, _), _ = jax.lax.scan(
            step, (acc, lse_acc, k_cur, v_cur), jnp.arange(1, n))
    return acc.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_shard_flash(q, k, v, axis_name, causal, sm_scale):
    return _ring_flash_shard(q, k, v, axis_name, causal, sm_scale)


def _ring_shard_flash_fwd(q, k, v, axis_name, causal, sm_scale):
    out = _ring_shard_flash(q, k, v, axis_name, causal, sm_scale)
    return out, (q, k, v)


def _ring_shard_flash_bwd(axis_name, causal, sm_scale, res, g):
    # Recompute through the XLA reference ring (ppermute and scan both
    # have transpose rules) — the flash forward's memory win stands, the
    # backward matches the reference shard bit-for-bit in math.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _ring_attention_shard(
            q_, k_, v_, axis_name=axis_name, causal=causal,
            sm_scale=sm_scale),
        q, k, v,
    )
    return vjp(g)


_ring_shard_flash.defvjp(_ring_shard_flash_fwd, _ring_shard_flash_bwd)


def ring_attention(q, k, v, mesh, axis_name="data", causal=False,
                   sm_scale=None, impl="auto"):
    """Ring attention over sequence-sharded [B, H, T, d] tensors.

    q/k/v are GLOBAL arrays; the mesh axis ``axis_name`` shards the
    sequence (dim 2). Returns the global output with the same sharding.

    impl: "auto" (flash blocks on TPU targets, XLA reference elsewhere),
    "flash" (force the Pallas per-block engine — interpret mode off-TPU),
    or "reference".
    """
    from paddle_tpu.kernels.flash_attention import _is_tpu_target

    shard_map = _shard_map()

    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if impl not in ("auto", "flash", "reference"):
        raise ValueError(
            "ring_attention: impl must be 'auto', 'flash' or 'reference'"
            ", got %r" % (impl,))
    use_flash = impl == "flash" or (impl == "auto" and _is_tpu_target())
    spec = P(None, None, axis_name, None)
    sm_kwargs = dict(mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)
    if use_flash:
        # custom_vjp takes its nondiff args positionally
        def body(q_, k_, v_):
            return _ring_shard_flash(q_, k_, v_, axis_name, causal,
                                     sm_scale)

        # pallas_call out_shapes carry no varying-axis (vma) annotation,
        # which newer shard_map's type checker rejects; the check is a
        # static lint, not a semantic change — disable it for this body
        # (check_rep is its pre-rename twin on older jax)
        try:
            fn = shard_map(body, check_vma=False, **sm_kwargs)
        except TypeError:  # older jax: the kwarg is named check_rep
            try:
                fn = shard_map(body, check_rep=False, **sm_kwargs)
            except TypeError:
                fn = shard_map(body, **sm_kwargs)
    else:
        fn = shard_map(
            functools.partial(
                _ring_attention_shard, axis_name=axis_name, causal=causal,
                sm_scale=sm_scale),
            **sm_kwargs)
    return fn(q, k, v)


def _ulysses_shard(q, k, v, axis_name, causal, sm_scale):
    """Per-shard body. q,k,v: [B, H, Tl, d]; requires H % n == 0."""
    # public entry: Pallas flash kernel on TPU targets, XLA reference on
    # CPU (pallas_call composes with shard_map)
    from paddle_tpu.kernels.flash_attention import flash_attention

    # [B, H, Tl, d] -> all_to_all -> [B, H/n, T, d]
    def seq_to_head(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def head_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh = seq_to_head(q)
    kh = seq_to_head(k)
    vh = seq_to_head(v)
    out = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis_name="data", causal=False,
                      sm_scale=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention."""
    shard_map = _shard_map()

    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError(
            "ulysses_attention needs heads (%d) divisible by axis size (%d)"
            % (q.shape[1], n)
        )
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            _ulysses_shard,
            axis_name=axis_name,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
