"""Sharding transpiler: derive a ``data x fsdp x tp`` GSPMD plan from the
op graph.

This replaces the pserver-era ``distribute_transpiler`` *planning* role
(slice_variable deciding which rows live on which pserver) with the GSPMD
equivalent: walk the Program's op graph once and annotate every VarDesc
with a PartitionSpec over the named mesh axes, so ``ParallelExecutor``
can shard a model with **zero hand-written layout entries**. The axis
semantics follow the scaling-book recipe (SNIPPETS [1] ``SpecLayout``):

* ``data`` — pure data parallelism: batch dims shard over it, params
  replicate, gradients all-reduce;
* ``fsdp`` — data parallelism that ALSO shards parameters/optimizer
  state (ZeRO-ish): batch dims shard over ``data x fsdp``, params shard
  a dim over ``fsdp`` (all-gather on use, reduce-scatter on grads);
* ``tp`` — tensor (model) parallelism: Megatron column/row splits on
  matmul weights, vocab splits on embeddings.

Canonical per-op rules (the table docs/DISTRIBUTED_DESIGN.md documents):

  mul/matmul (param Y)   column-parallel ``P(fsdp, tp)`` — or, when the
                         input activation already carries a tp-sharded
                         feature dim, row-parallel ``P(tp, fsdp)`` with
                         the implied psum charged to the tp axis
  lookup_table (W)       vocab-sharded ``P((fsdp, tp), None)``
  conv2d* (Filter)       ``P(fsdp, ...)`` on the out-channel dim
  batch_norm/layer_norm  stats/scale/bias replicated; activations stay
                         batch-sharded (reductions are global under jit)
  elementwise/reshape/   propagate batch and tp tags through
  transpose/split/...

Conflict resolution inserts an explicit *resharding point* (a
``jax.lax.with_sharding_constraint`` applied by the lowering at the
producing op — see core/lowering.py) rather than silently replicating:
e.g. tp-partial logits flowing into a loss reduction get constrained
back to batch-sharded/replicated-features exactly once, visibly.

Every fallback to replication is recorded in ``plan.notes`` ("no silent
caps"), and hand-written ``sharding_overrides`` remain an *override* on
top of the derived plan, validated by analysis rule S001
(analysis/shard_check.py) at transpile time.
"""

import logging

import numpy as np

from paddle_tpu.analysis.shard_check import (
    _mesh_axes_dict,
    check_sharding,
    normalize_spec,
    spec_axes,
    spec_shard_factor,
)

__all__ = [
    "ShardingPlan", "DerivedShardingPolicy", "derive_sharding",
    "record_collective_bytes", "plan_shard_factors", "MIN_SHARD_NUMEL",
]

logger = logging.getLogger("paddle_tpu.parallel")

# Params below this element count replicate: the per-step collective to
# gather a tiny sharded bias costs more than the bytes it saves (same
# threshold the legacy dim-0 "reduce" policy used).
MIN_SHARD_NUMEL = 1024

# Ops whose outputs keep their inputs' batch/tp tags verbatim.
_PROPAGATE_OPS = frozenset((
    "relu6", "brelu", "elu", "leaky_relu", "prelu", "soft_relu", "swish",
    "stanh", "hard_sigmoid", "hard_shrink", "softshrink",
    "thresholded_relu", "scale", "cast", "dropout", "softmax",
    "log_softmax", "clip", "pad", "pad2d", "label_smooth", "pow",
    "one_hot", "add_position_encoding", "rotary_embedding",
    "scaled_dot_product_attention", "l2_normalize", "cumsum",
))
_PROPAGATE_PREFIXES = ("elementwise_",)
# unary activation wrappers (layers/ops.py) all lower through these names
_PROPAGATE_UNARY = frozenset((
    "sigmoid", "logsigmoid", "exp", "relu", "gelu", "tanh", "tanh_shrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "log", "square", "softplus", "softsign",
))
# shape surgery: tags flow through, batch tag only while dim 0 survives
_RESHAPEY_OPS = frozenset((
    "reshape", "reshape2", "flatten", "flatten2", "squeeze", "squeeze2",
    "unsqueeze", "unsqueeze2", "split", "concat", "stack", "slice",
    "expand", "transpose", "transpose2",
))
# batch-sharded compute whose params stay replicated
_NORM_OPS = frozenset(("batch_norm", "layer_norm", "group_norm",
                       "affine_channel"))
_CONV_OPS = frozenset(("conv2d", "depthwise_conv2d", "conv3d",
                       "conv2d_transpose", "conv3d_transpose",
                       "depthwise_conv2d_transpose"))
_POOL_OPS = frozenset(("pool2d", "pool3d", "max_pool2d_with_index",
                       "max_pool3d_with_index", "lrn", "spp"))


class ShardingPlan(object):
    """The derived plan: var -> PartitionSpec (as plain tuples), plus the
    audit trail (fallback notes, reshard points, per-axis collective-byte
    estimates). ``specs`` holds every annotated var; ``param_specs()`` /
    ``feed_specs()`` filter by kind for the executor."""

    def __init__(self, mesh_axes):
        self.mesh_axes = {str(a): int(s) for a, s in dict(mesh_axes).items()}
        self.specs = {}        # name -> normalized spec tuple
        self.kinds = {}        # name -> "param" | "feed" | "activation"
        self.notes = {}        # name -> why it fell back / was overridden
        self.reshard_points = []  # {"var", "op_idx", "op_type", "spec"}
        self.collective_bytes = {}  # axis -> predicted bytes per step

    def _set(self, name, spec, kind, note=None):
        self.specs[name] = normalize_spec(spec)
        self.kinds[name] = kind
        if note:
            self.notes[name] = note

    def spec(self, name):
        return self.specs.get(name)

    def _by_kind(self, kind):
        return {n: s for n, s in self.specs.items()
                if self.kinds.get(n) == kind}

    def param_specs(self):
        return self._by_kind("param")

    def feed_specs(self):
        return self._by_kind("feed")

    def shard_factor(self, name):
        """How many devices split var ``name`` (1 = replicated)."""
        spec = self.specs.get(name)
        if not spec:
            return 1
        return spec_shard_factor(spec, self.mesh_axes)

    def sharded_params(self):
        return sorted(n for n in self.param_specs()
                      if self.shard_factor(n) > 1)

    def summary(self):
        """Compact dict for captures/benches: mesh axes, per-kind counts,
        how many params shard over which axes, reshard points."""
        params = self.param_specs()
        axis_counts = {}
        for n in params:
            for a in spec_axes(self.specs[n]):
                axis_counts[a] = axis_counts.get(a, 0) + 1
        return {
            "mesh_axes": dict(self.mesh_axes),
            "params": len(params),
            "params_sharded": len(self.sharded_params()),
            "params_by_axis": axis_counts,
            "feeds": len(self.feed_specs()),
            "activations_annotated": len(self._by_kind("activation")),
            "reshard_points": len(self.reshard_points),
            "fallbacks": len(self.notes),
            "collective_bytes": dict(self.collective_bytes),
        }

    def as_dict(self):
        return {
            "mesh_axes": dict(self.mesh_axes),
            "specs": {n: _spec_str(s) for n, s in sorted(self.specs.items())},
            "kinds": dict(self.kinds),
            "notes": dict(self.notes),
            "reshard_points": [dict(r) for r in self.reshard_points],
            "collective_bytes": dict(self.collective_bytes),
        }

    def __repr__(self):
        s = self.summary()
        return ("ShardingPlan(mesh=%s, %d/%d params sharded, "
                "%d reshard points)" % (s["mesh_axes"], s["params_sharded"],
                                        s["params"], s["reshard_points"]))


def _spec_str(spec):
    return "P(%s)" % ", ".join(
        "None" if e is None else
        ("(%s)" % ",".join(e) if isinstance(e, tuple) else e)
        for e in spec) if spec else "P()"


def _numel(shape):
    n = 1
    for d in shape:
        n *= max(1, int(d))
    return n


def _var_bytes(v, batch_size):
    """Logical bytes of one var, dynamic (-1) dims priced at
    ``batch_size`` — the collective-estimate discipline, matching
    observability/memory.py's accounting."""
    if v is None or v.shape is None:
        return 0
    size = 1
    for d in v.shape:
        d = int(d)
        size *= d if d > 0 else max(1, int(batch_size))
    try:
        item = np.dtype(str(v.dtype)).itemsize
    except Exception:
        item = 4
    return size * item


class _Deriver(object):
    def __init__(self, program, axes, overrides, feed_shapes, batch_size,
                 min_shard_numel):
        self.program = program
        self.block = program.global_block()
        self.axes = axes
        self.overrides = {n: normalize_spec(s)
                          for n, s in (overrides or {}).items()}
        self.feed_shapes = dict(feed_shapes or {})
        self.batch_size = batch_size
        self.min_numel = min_shard_numel
        self.plan = ShardingPlan(axes)
        self.data_n = axes.get("data", 1)
        self.fsdp_n = axes.get("fsdp", 1)
        self.tp_n = axes.get("tp", 1)
        # batch dims shard over every data-parallel axis present
        self.batch_axes = tuple(a for a in ("data", "fsdp") if a in axes)
        self.batch_ways = self.data_n * self.fsdp_n
        self.batch_vars = set()   # vars whose dim 0 is the global batch
        self.tp_vars = set()      # vars carrying a tp-sharded feature dim
        self.batch_ok = True      # concrete batch divides the batch axes

    # -- small helpers ------------------------------------------------------

    def _var(self, name):
        return self.block._find_var_recursive(name)

    def _is_param(self, name):
        from paddle_tpu.framework import Parameter

        return isinstance(self._var(name), Parameter)

    def _note(self, name, why):
        self.plan.notes[name] = why
        logger.info("derive_sharding: %s -> replicated dim (%s)", name, why)

    def _axis_fits(self, name, dim_size, axis_n, why_tag):
        """One dim, one axis: shardable iff the axis divides the dim."""
        if axis_n <= 1:
            return False
        if dim_size is None or int(dim_size) <= 0:
            return False
        if int(dim_size) % axis_n:
            self._note(name, "%s axis %d does not divide dim of size %d"
                       % (why_tag, axis_n, dim_size))
            return False
        return True

    def _set_param(self, name, spec, note=None):
        if name in self.overrides:
            self.plan._set(name, self.overrides[name], "param",
                           note="override (derived %s)" % _spec_str(
                               normalize_spec(spec)))
            return
        if name in self.plan.specs:
            # conflict: two use sites derived different layouts — the
            # FIRST wins (its collectives were already priced); a
            # differing second demand is recorded, not silently merged
            old = self.plan.specs[name]
            new = normalize_spec(spec)
            if old != new:
                self._note(name, "conflicting derived specs %s vs %s; "
                           "kept the first, consumer reshards"
                           % (_spec_str(old), _spec_str(new)))
            return
        self.plan._set(name, spec, "param", note=note)

    def _tag_out(self, op, batch=None, tp=None):
        for name in op.output_arg_names():
            if not name:
                continue
            if batch:
                self.batch_vars.add(name)
            if tp:
                self.tp_vars.add(name)

    def _inputs_tagged(self, op):
        ins = [n for n in op.input_arg_names() if n]
        return (any(n in self.batch_vars for n in ins),
                any(n in self.tp_vars for n in ins))

    def _charge(self, axis, nbytes):
        if nbytes > 0 and self.axes.get(axis, 1) > 1:
            self.plan.collective_bytes[axis] = (
                self.plan.collective_bytes.get(axis, 0) + int(nbytes))

    # -- feeds --------------------------------------------------------------

    def _seed_feeds(self):
        for name in sorted(self.block.vars):
            v = self.block.vars[name]
            if not getattr(v, "is_data", False):
                continue
            if name in self.overrides:
                # overrides win outright, feeds included (the legacy
                # ShardingPolicy honored feed overrides; so do we)
                self.plan._set(name, self.overrides[name], "feed",
                               note="override")
                continue
            shape = self.feed_shapes.get(name, v.shape)
            rank = len(shape) if shape is not None else None
            if not self.batch_axes or rank in (None, 0):
                self.plan._set(name, (), "feed",
                               note="scalar or unknown-rank feed" if rank
                               in (None, 0) else None)
                continue
            dim0 = int(shape[0])
            if dim0 > 0 and dim0 % self.batch_ways:
                self.plan._set(name, (), "feed",
                               note="batch %d not divisible by %d-way "
                               "data x fsdp" % (dim0, self.batch_ways))
                self.batch_ok = False
                continue
            self.plan._set(
                name, (self.batch_axes,) + (None,) * (rank - 1), "feed")
            self.batch_vars.add(name)

    # -- per-op rules -------------------------------------------------------

    def _rule_matmul(self, op, op_idx):
        xs = op.input("X") or op.input("Input")
        ys = op.input("Y") or op.input("W")
        outs = op.output("Out")
        if not xs or not ys or not outs:
            return
        x, y, out = xs[0], ys[0], outs[0]
        x_batch = x in self.batch_vars
        x_tp = x in self.tp_vars
        yv = self._var(y)
        if not self._is_param(y) or yv is None or yv.shape is None \
                or len(yv.shape) != 2:
            # activation x activation (attention scores etc.): tags flow
            self._tag_out(op, batch=x_batch, tp=x_tp or y in self.tp_vars)
            return
        rows, cols = int(yv.shape[0]), int(yv.shape[1])
        # "matmul" spells it transpose_Y (ops/math_ops.py); "mul" has none
        transpose_y = bool(op.attrs.get("transpose_Y", False))
        if transpose_y:
            rows, cols = cols, rows
        small = _numel(yv.shape) < self.min_numel
        if small:
            self._set_param(y, (), note="numel %d < %d threshold"
                            % (_numel(yv.shape), self.min_numel))
            self._tag_out(op, batch=x_batch, tp=False)
            return
        row_parallel = x_tp
        if row_parallel:
            # contracted dim already tp-sharded: shard W's rows over tp
            # (local partial matmul + psum), park fsdp on the cols
            r = "tp" if self._axis_fits(y, rows, self.tp_n, "tp") else None
            c = "fsdp" if self._axis_fits(y, cols, self.fsdp_n, "fsdp") \
                else None
            spec = (r, c)
            if transpose_y:
                spec = (c, r)
            self._set_param(y, spec)
            if r:
                ov = self._var(out)
                self._charge("tp", _var_bytes(ov, self.batch_size))
            self._tag_out(op, batch=x_batch, tp=False)
        else:
            # column-parallel: rows carry fsdp (storage), cols carry tp
            r = "fsdp" if self._axis_fits(y, rows, self.fsdp_n, "fsdp") \
                else None
            c = "tp" if self._axis_fits(y, cols, self.tp_n, "tp") else None
            spec = (r, c)
            if transpose_y:
                spec = (c, r)
            self._set_param(y, spec)
            self._tag_out(op, batch=x_batch, tp=bool(c))

    def _rule_lookup(self, op, op_idx):
        ws = op.input("W")
        outs = op.output("Out")
        if not ws:
            return
        w = ws[0]
        wv = self._var(w)
        if wv is None or wv.shape is None or not self._is_param(w):
            return
        vocab = int(wv.shape[0])
        if _numel(wv.shape) < self.min_numel:
            self._set_param(w, (), note="numel %d < %d threshold"
                            % (_numel(wv.shape), self.min_numel))
        else:
            # vocab rows shard over fsdp x tp together when divisible,
            # degrading one axis at a time before giving up
            for entry, ways in ((("fsdp", "tp"), self.fsdp_n * self.tp_n),
                                (("fsdp",), self.fsdp_n),
                                (("tp",), self.tp_n)):
                if ways > 1 and vocab % ways == 0:
                    self._set_param(
                        w, (entry,) + (None,) * (len(wv.shape) - 1))
                    if "tp" in entry:
                        # out-of-shard rows resolve via psum over tp
                        self._charge("tp", _var_bytes(
                            self._var(outs[0]) if outs else None,
                            self.batch_size))
                    break
            else:
                if self.fsdp_n * self.tp_n > 1:
                    self._set_param(w, (), note="vocab %d not divisible "
                                    "by fsdp x tp (%d)"
                                    % (vocab, self.fsdp_n * self.tp_n))
        ids_batch = any(n in self.batch_vars for n in op.input("Ids"))
        self._tag_out(op, batch=ids_batch, tp=False)

    def _rule_conv(self, op, op_idx):
        fs = op.input("Filter")
        if fs:
            w = fs[0]
            wv = self._var(w)
            if self._is_param(w) and wv is not None and wv.shape:
                if _numel(wv.shape) < self.min_numel:
                    self._set_param(w, (), note="numel %d < %d threshold"
                                    % (_numel(wv.shape), self.min_numel))
                elif self._axis_fits(w, wv.shape[0], self.fsdp_n, "fsdp"):
                    self._set_param(
                        w, ("fsdp",) + (None,) * (len(wv.shape) - 1))
                else:
                    self._set_param(w, ())
        batch, _tp = self._inputs_tagged(op)
        self._tag_out(op, batch=batch, tp=False)

    def _rule_norm(self, op, op_idx):
        for slot in ("Scale", "Bias", "Mean", "Variance"):
            for name in op.input(slot):
                if name and self._is_param(name) or (
                        name and self._var(name) is not None
                        and self._var(name).persistable):
                    self._set_param(name, (), note="norm statistics stay "
                                    "replicated (reductions are global "
                                    "under jit)")
        batch, tp = self._inputs_tagged(op)
        self._tag_out(op, batch=batch, tp=tp)

    def _rule_generic_param(self, op, op_idx):
        """Default for params consumed by ops with no specific rule:
        fsdp-shard dim 0 when it divides and the var is big enough."""
        batch, tp = self._inputs_tagged(op)
        for name in op.input_arg_names():
            if not name or not self._is_param(name) \
                    or name in self.plan.specs:
                continue
            v = self._var(name)
            if v is None or v.shape is None or not v.shape:
                continue
            if _numel(v.shape) < self.min_numel:
                self._set_param(name, (), note="numel %d < %d threshold"
                                % (_numel(v.shape), self.min_numel))
            elif self._axis_fits(name, v.shape[0], self.fsdp_n, "fsdp"):
                self._set_param(
                    name, ("fsdp",) + (None,) * (len(v.shape) - 1))
            else:
                self._set_param(name, ())
        self._tag_out(op, batch=batch, tp=tp)

    def _maybe_reshard(self, op, op_idx):
        """Conflict resolution: a tp-partial activation flowing into an
        op that reduces/consumes it with no tp story (losses, metrics,
        full reductions) gets an explicit resharding point at its
        producer — batch stays sharded, features go whole — instead of
        the weight silently replicating."""
        for name in op.input_arg_names():
            if name in self.tp_vars:
                v = self._var(name)
                rank = len(v.shape) if (v is not None and
                                        v.shape is not None) else 1
                batch0 = (self.batch_axes if (
                    name in self.batch_vars and self.batch_axes
                    and self.batch_ok) else None)
                spec = (batch0,) + (None,) * (rank - 1) if rank else ()
                self.plan.reshard_points.append({
                    "var": name, "op_idx": op_idx, "op_type": op.type,
                    "spec": _spec_str(normalize_spec(spec))})
                if v is not None:
                    v.reshard_spec = normalize_spec(spec)
                self._charge("tp", _var_bytes(v, self.batch_size))
                self.tp_vars.discard(name)

    # -- the walk -----------------------------------------------------------

    def derive(self):
        from paddle_tpu.framework import OpRole, OP_ROLE_ATTR_NAME

        self._clear_annotations()
        self._seed_feeds()
        for op_idx, op in enumerate(self.block.ops):
            role = op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)
            if role not in (OpRole.Forward, OpRole.Loss,
                            OpRole.Forward | OpRole.Loss):
                continue  # backward/optimize follow the forward layout
            t = op.type
            if t in ("mul", "matmul"):
                self._rule_matmul(op, op_idx)
            elif t == "lookup_table":
                self._rule_lookup(op, op_idx)
            elif t in _CONV_OPS:
                self._rule_conv(op, op_idx)
            elif t in _NORM_OPS:
                self._rule_norm(op, op_idx)
            elif (t in _PROPAGATE_OPS or t in _PROPAGATE_UNARY
                  or t.startswith(_PROPAGATE_PREFIXES)):
                # params riding along (biases, learned embeddings added
                # elementwise) get the generic rule: tiny ones replicate
                # with a note, big divisible ones fsdp-shard dim 0 —
                # never a silent un-noted replication
                self._rule_generic_param(op, op_idx)
            elif t in _RESHAPEY_OPS:
                batch, tp = self._inputs_tagged(op)
                if batch and not self._keeps_batch_dim(op):
                    batch = False
                self._tag_out(op, batch=batch, tp=tp)
            elif t in _POOL_OPS:
                batch, _tp = self._inputs_tagged(op)
                self._tag_out(op, batch=batch, tp=False)
            elif t in ("mean", "reduce_sum", "reduce_mean", "reduce_max",
                       "reduce_min", "cross_entropy",
                       "softmax_with_cross_entropy", "accuracy",
                       "square_error_cost", "sum", "top_k", "arg_max",
                       "fetch"):
                self._maybe_reshard(op, op_idx)
                # per-row losses keep the batch dim; scalars drop it
                batch, _tp = self._inputs_tagged(op)
                for name in op.output_arg_names():
                    v = self._var(name)
                    if (batch and v is not None and v.shape
                            and len(v.shape) >= 1):
                        self.batch_vars.add(name)
            else:
                self._rule_generic_param(op, op_idx)

        self._annotate_activations()
        self._inherit_accumulators()
        self._apply_leftover_overrides()
        self._price_param_collectives()
        self._write_annotations()
        return self.plan

    def _keeps_batch_dim(self, op):
        """Dim 0 survives: transpose keeping axis 0 first, reshape whose
        leading dim is -1/unchanged, split/concat off dim 0, etc."""
        t = op.type
        if t in ("transpose", "transpose2"):
            perm = op.attrs.get("axis") or op.attrs.get("perm") or ()
            return not perm or list(perm)[0] == 0
        if t in ("split", "concat", "stack", "slice"):
            dim = op.attrs.get("dim", op.attrs.get("axis", -1))
            axes = op.attrs.get("axes", None)
            if t == "slice":
                return not axes or 0 not in list(axes)
            return dim != 0
        if t in ("reshape", "reshape2", "flatten", "flatten2"):
            ins = [n for n in op.input_arg_names() if n]
            outs = [n for n in op.output_arg_names() if n]
            if ins and outs:
                vi, vo = self._var(ins[0]), self._var(outs[0])
                if (vi is not None and vo is not None and vi.shape
                        and vo.shape):
                    return int(vi.shape[0]) == int(vo.shape[0]) or (
                        int(vi.shape[0]) < 0 and int(vo.shape[0]) < 0)
            shape_attr = op.attrs.get("shape") or ()
            return bool(shape_attr) and int(shape_attr[0]) in (-1, 0)
        return True  # squeeze/unsqueeze/expand of trailing dims

    def _annotate_activations(self):
        if not (self.batch_axes and self.batch_ok):
            return
        for name in self.batch_vars:
            if name in self.plan.specs:
                continue
            v = self._var(name)
            if v is None or v.shape is None or not v.shape:
                continue
            self.plan._set(
                name, (self.batch_axes,) + (None,) * (len(v.shape) - 1),
                "activation",
                note="tp-partial features" if name in self.tp_vars
                else None)

    def _inherit_accumulators(self):
        """Optimizer accumulators ('<param>_moment_0' etc.) declared in
        the program inherit their param's layout when same-shaped, so
        moments partition exactly like the weight (the mesh.py prefix
        rule, resolved statically here)."""
        params = self.plan.param_specs()
        for name in sorted(self.block.vars):
            if name in self.plan.specs:
                continue
            v = self.block.vars[name]
            if not getattr(v, "persistable", False) or v.shape is None:
                continue
            for base, spec in params.items():
                if name.startswith(base + "_") and tuple(v.shape) == tuple(
                        getattr(self._var(base), "shape", ()) or ()):
                    self.plan._set(name, spec, "param",
                                   note="inherits %s" % base)
                    break

    def _price_param_collectives(self):
        """Per-axis per-step collective-byte estimates for the plan's
        params: grads all-reduce over pure-data axes; fsdp-sharded
        params all-gather + their grads reduce-scatter (2x bytes);
        fsdp-replicated params still all-reduce grads over fsdp."""
        from paddle_tpu.framework import Parameter

        for name, spec in self.plan.param_specs().items():
            v = self._var(name)
            nbytes = _var_bytes(v, self.batch_size)
            if not nbytes:
                continue
            if not isinstance(v, Parameter) or getattr(
                    v, "stop_gradient", False):
                # optimizer accumulators (sharding-aligned updates, no
                # gather) and non-trainable state (BN stats): no grad or
                # fsdp traffic of their own
                continue
            axes_used = set(spec_axes(spec))
            if self.data_n > 1:
                self._charge("data", nbytes)
            if self.fsdp_n > 1:
                self._charge("fsdp",
                             2 * nbytes if "fsdp" in axes_used else nbytes)

    def _clear_annotations(self):
        """Drop annotations a PREVIOUS derivation stamped (possibly under
        a different mesh or overrides): a var this plan never touches
        must not keep — and core/lowering.py must not apply — the old
        plan's spec. (A cached plan skips derive(), so two executors
        alternating derivations over one program can still interleave
        stamps; each fresh derivation at least starts from zero.)"""
        for block in self.program.blocks:
            for v in block.vars.values():
                if hasattr(v, "partition_spec"):
                    del v.partition_spec
                if hasattr(v, "reshard_spec"):
                    del v.reshard_spec

    def _apply_leftover_overrides(self):
        """Overrides win outright — including for vars no op rule or
        feed/accumulator sweep reached (S001 already validated them
        against the program and mesh)."""
        for name, spec in self.overrides.items():
            if name in self.plan.specs:
                continue
            v = self._var(name)
            if getattr(v, "is_data", False):
                kind = "feed"
            elif self._is_param(name) or getattr(v, "persistable", False):
                kind = "param"
            else:
                kind = "activation"
            self.plan._set(name, spec, kind,
                           note="override (no derivation rule reached it)")

    def _write_annotations(self):
        """Stamp every derived spec onto its VarDesc so the plan is
        inspectable (debugger.program_to_code) without running it."""
        for name, spec in self.plan.specs.items():
            v = self._var(name)
            if v is not None:
                v.partition_spec = spec


def derive_sharding(program, mesh_axes, overrides=None, feed_shapes=None,
                    batch_size=None, min_shard_numel=MIN_SHARD_NUMEL,
                    validate=True):
    """Derive a :class:`ShardingPlan` for ``program`` over ``mesh_axes``
    (a ``jax.sharding.Mesh`` or an ``{axis: size}`` dict using the
    ``data``/``fsdp``/``tp`` names).

    ``overrides`` (the old hand-written ``tp_layout`` surface) take
    precedence over the derived specs and are validated by analysis rule
    S001 first — a bad override raises
    :class:`analysis.ProgramVerifyError` here, at transpile time, not as
    an XLA shape error mid-compile. ``feed_shapes`` resolves dynamic
    batch dims so batch-axis divisibility is checked for real; without
    it the plan assumes a divisible batch and the runtime feed fallback
    still protects execution. Annotates every planned var's
    ``Variable.partition_spec`` (and conflict vars' ``reshard_spec``,
    which core/lowering.py turns into an explicit
    ``with_sharding_constraint``).
    """
    axes = _mesh_axes_dict(mesh_axes)
    if validate and overrides:
        from paddle_tpu.analysis.diagnostics import (
            ProgramVerifyError, at_or_above)

        diags = check_sharding(program, axes, overrides,
                               origin="sharding override")
        errors = at_or_above(diags, "error")
        if errors:
            raise ProgramVerifyError(errors, origin="derive_sharding")
    if batch_size is None:
        batch_size = 1
        for s in (feed_shapes or {}).values():
            if s and int(s[0]) > 0:
                batch_size = max(batch_size, int(s[0]))
    d = _Deriver(program, axes, overrides, feed_shapes, batch_size,
                 min_shard_numel)
    return d.derive()


class DerivedShardingPolicy(object):
    """A :class:`ShardingPlan` in the ``ShardingPolicy`` interface the
    executors consume (``mesh`` / ``state_sharding`` / ``feed_sharding``
    / ``replicated`` / ``plan``): the derived specs become the in/out
    shardings of the single jitted executable. Vars the plan never saw
    (scalar LR counters, beta pows) replicate; optimizer accumulators
    created AFTER derivation still inherit their param's layout through
    the same prefix+shape rule mesh.ShardingPolicy applies."""

    strategy = "derived"

    def __init__(self, mesh, plan, state_shapes=None):
        from jax.sharding import NamedSharding, PartitionSpec

        self.mesh = mesh
        self.derived = plan
        self.state_shapes = dict(state_shapes or {})
        self._NamedSharding = NamedSharding
        self._PartitionSpec = PartitionSpec
        self._logged = set()

    def replicated(self):
        return self._NamedSharding(self.mesh, self._PartitionSpec())

    def _spec_to_sharding(self, spec):
        return self._NamedSharding(
            self.mesh, self._PartitionSpec(*normalize_spec(spec)))

    def _derived_spec(self, name):
        spec = self.derived.specs.get(name)
        if spec is not None:
            return spec
        # late-created accumulators ("<param>_moment1_0"): inherit the
        # param's layout when same-shaped (same rule the legacy policy
        # applies dynamically; derive-time inheritance only covers vars
        # already declared in the program)
        shape = self.state_shapes.get(name)
        if shape is not None:
            for base, pspec in self.derived.param_specs().items():
                if name.startswith(base + "_") and tuple(shape) == tuple(
                        self.state_shapes.get(base, ())):
                    return pspec
        return None

    def state_sharding(self, name):
        spec = self._derived_spec(name)
        if spec:
            return self._spec_to_sharding(spec)
        return self.replicated()

    def feed_sharding(self, name, shape=None):
        spec = self.derived.specs.get(name)
        if spec is None:
            # a feed the derivation never saw (derived without
            # feed_shapes, or a var fed ad hoc): batch-shard when the
            # concrete shape divides, replicate otherwise
            axes = tuple(a for a in ("data", "fsdp")
                         if self.derived.mesh_axes.get(a, 1) >= 1
                         and a in self.derived.mesh_axes)
            ways = 1
            for a in axes:
                ways *= self.derived.mesh_axes[a]
            if (shape is None or not len(shape) or ways <= 1
                    or int(shape[0]) % ways):
                if name not in self._logged:
                    self._logged.add(name)
                    logger.info(
                        "derived sharding fallback: feed %s -> replicated "
                        "(shape %s not divisible by %d-way batch axes)",
                        name, tuple(shape) if shape is not None else None,
                        ways)
                return self.replicated()
            return self._spec_to_sharding((axes,))
        if shape is not None and spec:
            # concrete shape wins over the derive-time assumption
            factor = 1
            for a in spec_axes((spec[0],) if spec else ()):
                factor *= self.derived.mesh_axes.get(a, 1)
            if len(shape) and factor > 1 and int(shape[0]) % factor:
                if name not in self._logged:
                    self._logged.add(name)
                    logger.info(
                        "derived sharding fallback: feed %s -> replicated "
                        "(batch %d not divisible by %d)", name,
                        int(shape[0]), factor)
                return self.replicated()
        return self._spec_to_sharding(spec)

    def plan(self):
        """name -> (spec str, note) for observability — the same contract
        mesh.ShardingPolicy.plan() has, fed from the derived plan."""
        out = {}
        for name in sorted(self.derived.specs):
            out[name] = (_spec_str(self.derived.specs[name]),
                         self.derived.notes.get(name, ""))
        return out


def plan_shard_factors(plan):
    """{var name -> ways split} for every var the plan shards — the
    divisor Program.memory_plan applies so the predicted peak reflects
    per-device bytes, not logical bytes."""
    out = {}
    for name in plan.specs:
        f = plan.shard_factor(name)
        if f > 1:
            out[name] = f
    return out


def record_collective_bytes(plan):
    """Export the plan's per-axis collective-byte estimates as labeled
    gauges (``paddle_tpu_collective_bytes{axis}``) — the topology-traffic
    twin of the PR 4 straggler/imbalance metrics, refreshed once per
    compile, never per step."""
    from paddle_tpu.observability.metrics_registry import REGISTRY

    g = REGISTRY.gauge(
        "paddle_tpu_collective_bytes",
        "predicted per-step collective traffic per mesh axis, from the "
        "derived sharding plan (grad all-reduce / fsdp gather+scatter / "
        "tp psum)", labels=("axis",))
    for axis in plan.mesh_axes:
        g.set(int(plan.collective_bytes.get(axis, 0)), axis=str(axis))
    return dict(plan.collective_bytes)
