"""Multi-device parallelism: mesh + sharding policies (GSPMD).

This package is the TPU-native replacement for the reference's entire
multi-device/multi-host stack: MultiDevSSAGraphBuilder + NCCL allreduce
(paddle/fluid/framework/details/), the gRPC parameter server
(operators/distributed/), and gen_nccl_id bootstrap — all become sharding
annotations over a jax.sharding.Mesh compiled by XLA into ICI/DCN
collectives.
"""

from paddle_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    ShardingPolicy,
    build_mesh,
    init_distributed,
)
from paddle_tpu.parallel.sharding import (  # noqa: F401
    DerivedShardingPolicy,
    ShardingPlan,
    derive_sharding,
    plan_shard_factors,
    record_collective_bytes,
)
from paddle_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from paddle_tpu.parallel.pipeline import (  # noqa: F401
    gpipe,
    stack_stage_params,
)
