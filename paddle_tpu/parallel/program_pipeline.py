"""Program-level pipeline parallelism: cut a fluid Program into S stages.

Reference capability: the transparent multi-device story of
``paddle/fluid/framework/details/multi_devices_graph_pass.cc`` — the user
writes an ordinary Program (layers + optimizer.minimize) and the executor
spreads it over devices. The reference spreads by DATA parallelism; this
module adds the pipeline dimension the same transparent way: ParallelExecutor
cuts the Program's forward into S stages, runs a GPipe microbatch schedule
over the mesh's ``pipe`` axis, and applies the Program's own optimizer ops —
no hand-stacked homogeneous blocks (that capability layer is
``parallel/pipeline.py:gpipe``; this is the front-end that subsumes it for
real models with heterogeneous per-stage parameters).

TPU-first design (one compiled SPMD program, no per-stage executables):

- **Cutting**: a valid cut point is an op boundary where exactly ONE
  non-persistable, non-feed var is live across it (the classic GPipe
  single-activation boundary); all chosen boundaries must agree on
  activation shape[1:]/dtype so the rotating carry is a single buffer.
  Cuts are chosen to balance parameter bytes per stage.
- **Heterogeneous stage params**: each stage's params are flattened and
  concatenated into one f32 vector, padded to the longest stage, and
  stacked [S, L] — sharded ``P("pipe")`` so device s holds ONLY stage s's
  weights (O(P/S) param memory). Inside the per-device body each stage's
  branch unpacks its own slices; ``lax.switch`` on the device's axis index
  dispatches the right stage function (SPMD-compatible heterogeneity:
  every device compiles all branches, runs one).
- **Schedule**: M microbatches flow through S stages in M+S-1 ticks of a
  ``lax.scan``; activations hop to the next device with ``lax.ppermute``
  (nearest-neighbor on ICI). Bubbles are skipped with ``lax.cond``.
- **Backward**: ``jax.grad`` of the whole pipelined loss — the transpose
  of ppermute/scan/switch IS the reverse pipeline schedule; no backward
  graph is cut or scheduled by hand.
- **Optimizer**: the Program's optimize-role ops are applied on the packed
  [S, L] vectors directly (elementwise updates vectorize over the packed
  layout and preserve the pipe sharding); LR-schedule ops and scalar
  accumulators (beta powers) lower on a replicated scalar environment via
  the ordinary op registry.
- **data parallelism**: with a 2-D (pipe, data) mesh the microbatch batch
  dim is sharded over "data"; GSPMD inserts the gradient psum across the
  data axis because the packed params are replicated along it.

Constraints (checked, with errors naming them): the forward must be
cuttable at single-var uniform boundaries (encoder-style stacks and MLPs
qualify; encoder-decoder cross-attention does not — its boundary carries
two live vars); all trainable params must share one optimizer op type,
attrs, and learning rate; forward ops must not write persistables (fold
BN-stats models into data parallelism instead); fetches are limited to
the loss.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core import op_registry
from paddle_tpu.core.lowering import BlockLowerer
from paddle_tpu.core.op_registry import LowerContext, normalize_outputs
from paddle_tpu.framework import OP_ROLE_ATTR_NAME, OpRole
from paddle_tpu.parallel import _compat

_NON_SEMANTIC_ATTRS = (OP_ROLE_ATTR_NAME, "op_role_var", "__rng_id__")


class _Segment(object):
    def __init__(self, ops, in_var, out_var):
        self.ops = ops
        self.in_var = in_var      # boundary var consumed (None for stage 0)
        self.out_var = out_var    # boundary var produced (loss for last)
        self.param_names = []     # persistable inputs, packing order
        self.feed_names = []


def _role(op):
    return op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)


def _split_roles(block):
    fwd, opt, lrsched = [], [], []
    for op in block.ops:
        r = _role(op)
        if r == OpRole.LRSched:
            lrsched.append(op)
        elif r & OpRole.Optimize:
            opt.append(op)
        elif r & OpRole.Backward:
            pass  # re-derived by jax.grad of the pipelined forward
        else:
            fwd.append(op)
    return fwd, opt, lrsched


def _var_bytes(v):
    if not v.shape:
        return 4
    return 4 * int(np.prod([abs(d) for d in v.shape]))


def _find_cuts(block, fwd_ops, feed_names, n_stages):
    """Choose n_stages-1 single-live-var cut points balancing param bytes."""
    produced_at = {}
    for i, op in enumerate(fwd_ops):
        for name in op.output_arg_names():
            if name:
                produced_at.setdefault(name, i)
    consumers = {}
    for i, op in enumerate(fwd_ops):
        for name in op.input_arg_names():
            if name:
                consumers.setdefault(name, []).append(i)

    def is_state(name):
        v = block._find_var_recursive(name)
        return v is not None and v.persistable

    # candidate cut at position p: live set {produced < p, consumed >= p}
    candidates = []
    for p in range(1, len(fwd_ops)):
        live = set()
        for name, start in produced_at.items():
            if start < p and not is_state(name) and name not in feed_names:
                if any(c >= p for c in consumers.get(name, ())):
                    live.add(name)
        if len(live) == 1:
            (name,) = live
            v = block._find_var_recursive(name)
            if v is None or v.shape is None:
                continue
            sig = (tuple(v.shape[1:]), str(v.dtype))
            candidates.append((p, name, sig))
    if not candidates:
        raise ValueError(
            "pipeline: no single-live-var cut point exists in the forward "
            "(multi-var boundaries — e.g. encoder-decoder cross attention "
            "— are not pipelineable by this pass)")

    # boundaries must agree on activation signature: take the modal group
    groups = {}
    for c in candidates:
        groups.setdefault(c[2], []).append(c)
    sig, group = max(groups.items(), key=lambda kv: len(kv[1]))
    if len(group) < n_stages - 1:
        raise ValueError(
            "pipeline: only %d uniform cut points (activation %s) but "
            "%d stages need %d cuts — lower pipeline_stages"
            % (len(group), sig, n_stages, n_stages - 1))

    # balance parameter bytes: weight[i] = bytes of params first READ at op i
    seen = set()
    weight = np.zeros(len(fwd_ops))
    for i, op in enumerate(fwd_ops):
        for name in op.input_arg_names():
            if name and name not in seen and is_state(name):
                seen.add(name)
                weight[i] = weight[i] + _var_bytes(
                    block._find_var_recursive(name))
    cum = np.cumsum(weight)
    total = float(cum[-1]) or 1.0
    group.sort(key=lambda c: c[0])
    cuts = []
    for s in range(1, n_stages):
        target = total * s / n_stages
        remaining_after = n_stages - 1 - s
        # a pick must stay increasing AND leave enough later candidates
        # for the cuts still to be placed (greedy-by-target alone could
        # grab a late position and strand the tail)
        feasible = [
            c for i, c in enumerate(group)
            if (not cuts or c[0] > cuts[-1][0])
            and len(group) - i - 1 >= remaining_after
        ]
        best = min(
            feasible,
            key=lambda c: abs(float(cum[c[0] - 1]) - target),
            default=None)
        if best is None:
            raise ValueError(
                "pipeline: could not place %d increasing cuts among the "
                "uniform candidates" % (n_stages - 1))
        cuts.append(best)
    return cuts


def _pack_layout(segments, block):
    """Per stage: [(name, offset, size, shape)] + the padded row length."""
    layouts, lengths = [], []
    for seg in segments:
        off, entries = 0, []
        for name in seg.param_names:
            v = block._find_var_recursive(name)
            if str(v.dtype) not in ("float32", "paddle_tpu_f32", "FP32"):
                # packed rows are one f32 buffer; params are f32 in this
                # framework (AMP casts at op boundaries, not in storage)
                raise ValueError(
                    "pipeline: param %r has dtype %s; only float32 params "
                    "are packable" % (name, v.dtype))
            shape = tuple(int(d) for d in v.shape)
            size = int(np.prod(shape)) if shape else 1
            entries.append((name, off, size, shape))
            off += size
        layouts.append(entries)
        lengths.append(off)
    return layouts, max(lengths) if lengths else 1


class PipelinedProgram(object):
    """One jitted pipelined train step for a minimize()'d Program."""

    def __init__(self, program, loss_name, feed_specs, mesh,
                 n_microbatches, axis_name="pipe", batch_axis=None):
        self.program = program
        self.loss_name = loss_name
        self.mesh = mesh
        self.axis_name = axis_name
        self.batch_axis = batch_axis
        self.n_stages = int(mesh.shape[axis_name])
        self.n_micro = int(n_microbatches)
        self.data_size = int(mesh.shape[batch_axis]) if batch_axis else 1
        if self.n_stages < 2:
            raise ValueError("pipeline needs a pipe axis of size >= 2")
        block = program.global_block()
        self.block = block
        self.lowerer = BlockLowerer(program, 0, is_test=False)

        fwd_ops, opt_ops, lrsched_ops = _split_roles(block)
        if not fwd_ops:
            raise ValueError("pipeline: program has no forward ops")
        self._check_no_persistable_writes(fwd_ops, block)
        self._build_segments(fwd_ops, set(feed_specs))
        self._classify_optimizer(opt_ops, lrsched_ops, block)
        self.layouts, self.row_len = _pack_layout(self.segments, block)
        self._record_stage_metrics()
        self._build_step(feed_specs)

    def _record_stage_metrics(self):
        """Per-stage balance + occupancy gauges, one series per stage.
        Recorded once per BUILD (never per step): an imbalanced cut —
        one stage holding most of the ops/params — is the pipeline's
        straggler, visible here before a single tick runs."""
        from paddle_tpu.observability import telemetry
        from paddle_tpu.observability.metrics_registry import REGISTRY

        telemetry.record_pipeline_occupancy(self.n_stages, self.n_micro)
        ops_g = REGISTRY.gauge(
            "paddle_tpu_pipeline_stage_ops",
            "forward ops per pipeline stage (cut balance)",
            labels=("stage",))
        bytes_g = REGISTRY.gauge(
            "paddle_tpu_pipeline_stage_param_bytes",
            "packed parameter bytes per pipeline stage",
            labels=("stage",))
        for s, seg in enumerate(self.segments):
            ops_g.set(len(seg.ops), stage="%d" % s)
            bytes_g.set(
                sum(_var_bytes(self.block._find_var_recursive(n))
                    for n in seg.param_names
                    if self.block._find_var_recursive(n) is not None),
                stage="%d" % s)

    # -- analysis ----------------------------------------------------------
    @staticmethod
    def _check_no_persistable_writes(fwd_ops, block):
        for op in fwd_ops:
            for name in op.output_arg_names():
                v = block._find_var_recursive(name) if name else None
                if v is not None and v.persistable:
                    raise ValueError(
                        "pipeline: forward op %r writes persistable %r "
                        "(running-stats models are not pipelineable; use "
                        "data parallelism)" % (op.type, name))

    def _build_segments(self, fwd_ops, feed_names):
        cuts = _find_cuts(self.block, fwd_ops, feed_names, self.n_stages)
        bounds = [0] + [c[0] for c in cuts] + [len(fwd_ops)]
        names = [c[1] for c in cuts]
        self.segments = []
        for s in range(self.n_stages):
            seg = _Segment(
                fwd_ops[bounds[s]:bounds[s + 1]],
                in_var=names[s - 1] if s > 0 else None,
                out_var=names[s] if s < self.n_stages - 1
                else self.loss_name,
            )
            produced = set()
            for op in seg.ops:
                for name in op.input_arg_names():
                    if not name or name in produced:
                        continue
                    v = self.block._find_var_recursive(name)
                    if v is not None and v.persistable:
                        if name not in seg.param_names:
                            seg.param_names.append(name)
                    elif name in feed_names and name not in seg.feed_names:
                        seg.feed_names.append(name)
                produced.update(op.output_arg_names())
            self.segments.append(seg)
        if not any(self.loss_name in op.output_arg_names()
                   for op in self.segments[-1].ops):
            raise ValueError(
                "pipeline: loss %r is not produced by the last stage"
                % self.loss_name)

    def _classify_optimizer(self, opt_ops, lrsched_ops, block):
        updates = [op for op in opt_ops
                   if op.input("Param") and op.input("Grad")]
        if not updates:
            raise ValueError(
                "pipeline: program has no optimizer update ops (call "
                "optimizer.minimize first)")
        tmpl = updates[0]
        sem = {k: v for k, v in tmpl.attrs.items()
               if k not in _NON_SEMANTIC_ATTRS}
        for op in updates[1:]:
            if op.type != tmpl.type or sem != {
                    k: v for k, v in op.attrs.items()
                    if k not in _NON_SEMANTIC_ATTRS}:
                raise ValueError(
                    "pipeline: all params must share one optimizer "
                    "(found %s vs %s)" % (tmpl.type, op.type))
            if op.input("LearningRate") != tmpl.input("LearningRate"):
                raise ValueError(
                    "pipeline: per-parameter learning rates are not "
                    "supported under the packed pipeline update")
        self.update_by_param = {op.input("Param")[0]: op for op in updates}
        self.update_template = tmpl
        self.update_attrs = sem
        opdef = op_registry.get_op_def(tmpl.type)
        # acc slots: same-shape-as-param -> packed [S, L]; [1] -> scalar env
        self.packed_slots, self.scalar_slots = [], []
        for slot in opdef.input_slots():
            if slot in ("Param", "Grad", "LearningRate"):
                continue
            if not tmpl.input(slot):
                continue
            name = tmpl.input(slot)[0]
            v = block._find_var_recursive(name)
            pshape = block._find_var_recursive(
                tmpl.input("Param")[0]).shape
            if tuple(v.shape or ()) == tuple(pshape or ()):
                if ("%sOut" % slot) not in opdef.output_slots():
                    raise ValueError(
                        "pipeline: optimizer slot %s has no %sOut output"
                        % (slot, slot))
                self.packed_slots.append(slot)
            else:
                self.scalar_slots.append(slot)
        # scalar ops: optimize-role ops that are not param updates (lr
        # scaling, beta-pow advance); split around the first update op
        first_update = min(block.ops.index(op) for op in updates)
        self.pre_scalar_ops = [
            op for op in opt_ops + lrsched_ops
            if op not in updates and block.ops.index(op) < first_update]
        self.post_scalar_ops = [
            op for op in opt_ops + lrsched_ops
            if op not in updates and block.ops.index(op) >= first_update]
        self.pre_scalar_ops.sort(key=block.ops.index)
        self.post_scalar_ops.sort(key=block.ops.index)
        # replicated scalar state: persistables read/written by scalar ops
        # and the scalar optimizer slots of EVERY param
        names = []
        for op in self.pre_scalar_ops + self.post_scalar_ops:
            names.extend(op.input_arg_names())
            names.extend(op.output_arg_names())
        names.extend(self.update_template.input("LearningRate"))
        for slot in self.scalar_slots:
            for op in updates:
                names.extend(op.input(slot))
        self.scalar_state = []
        for n in names:
            v = block._find_var_recursive(n) if n else None
            if v is not None and v.persistable and n not in self.scalar_state:
                self.scalar_state.append(n)

    # -- the compiled step --------------------------------------------------
    def _branch(self, s):
        seg = self.segments[s]
        layout = self.layouts[s]
        lowerer = self.lowerer
        is_last = s == self.n_stages - 1

        def run(local_vec, act, mb_feeds, key, zero_act, zero_loss):
            env = {}
            for name, off, size, shape in layout:
                flat = jax.lax.dynamic_slice(local_vec, (off,), (size,))
                env[name] = flat.reshape(shape) if shape else flat[0]
            for name in seg.feed_names:
                env[name] = mb_feeds[name]
            if seg.in_var is not None:
                env[seg.in_var] = act
            for op in seg.ops:
                lowerer.lower_op(op, env, key)
            # zero_act/zero_loss carry the varying-axes marking every
            # branch output must share (lax.switch type agreement)
            if is_last:
                loss = jnp.reshape(
                    env[self.loss_name], ()).astype(jnp.float32)
                return zero_act, zero_loss + loss
            return (zero_act + env[seg.out_var].astype(zero_act.dtype),
                    zero_loss)

        return run

    def _boundary_act_spec(self, feed_specs):
        """Trace stage 0 alone to learn the boundary activation shape for
        one LOCAL microbatch (batch dim = B / M / data_parallel)."""
        micro = self._micro_local(feed_specs)
        branch0 = self._branch(0)

        def probe(feeds):
            vec = jnp.zeros((self.row_len,), jnp.float32)
            mb = {n: feeds[n] for n in feeds}
            dummy = jnp.zeros((), jnp.float32)
            act, _ = branch0(vec, dummy, mb, jax.random.PRNGKey(0), dummy,
                            jnp.float32(0.0))
            return act

        specs = {
            n: jax.ShapeDtypeStruct((micro,) + tuple(shape[1:]), dtype)
            for n, (shape, dtype) in feed_specs.items()
        }
        # params in the probe are zeros of the right size: shape inference
        # only needs shapes, and stage 0's slices all fit in one row
        out = jax.eval_shape(probe, specs)
        return out.shape, out.dtype

    def _micro_local(self, feed_specs):
        any_shape = next(iter(feed_specs.values()))[0]
        b = any_shape[0]
        denom = self.n_micro * self.data_size
        if b % denom:
            raise ValueError(
                "pipeline: batch %d must divide microbatches*data = %d*%d"
                % (b, self.n_micro, self.data_size))
        return b // denom

    def _build_step(self, feed_specs):
        mesh = self.mesh
        axis = self.axis_name
        n, m = self.n_stages, self.n_micro
        act_shape, act_dtype = self._boundary_act_spec(feed_specs)
        branches = [self._branch(s) for s in range(n)]
        fwd_perm = [(i, i + 1) for i in range(n - 1)]
        batch_axis = self.batch_axis

        def _vary(x):
            x = _compat.vary(x, axis)
            return _compat.vary(x, batch_axis) if batch_axis else x

        def shard_body(vec, feeds, key):
            # vec [1, L]; feeds [M, micro_local, ...]
            d = jax.lax.axis_index(axis)
            local = vec[0]
            zero_act = _vary(jnp.zeros(act_shape, act_dtype))
            zero_loss = _vary(jnp.float32(0.0))
            ticks = m + n - 1

            def tick(carry, t):
                prev_out, loss_sum = carry
                recv = jax.lax.ppermute(prev_out, axis, fwd_perm)
                mb = t - d
                valid = (mb >= 0) & (mb < m)
                slot = jnp.clip(mb, 0, m - 1)
                mb_feeds = {
                    k: jax.lax.dynamic_index_in_dim(
                        v, slot, 0, keepdims=False)
                    for k, v in feeds.items()
                }
                tick_key = jax.random.fold_in(
                    jax.random.fold_in(key, t), d)

                def work(args):
                    act, mbf = args
                    return jax.lax.switch(
                        d, branches, local, act, mbf, tick_key, zero_act,
                        zero_loss)

                def bubble(args):
                    return zero_act, zero_loss

                safe_recv = jnp.where(valid, recv, zero_act)
                y, lval = jax.lax.cond(
                    valid, work, bubble, (safe_recv, mb_feeds))
                loss_sum = loss_sum + jnp.where(valid, lval, 0.0)
                return (y, loss_sum), None

            init = (zero_act, zero_loss)
            (_, loss_sum), _ = jax.lax.scan(
                tick, init, jnp.arange(ticks))
            # only the last device banked nonzero loss; share it out
            total = jax.lax.psum(loss_sum, axis) / m
            if batch_axis:
                total = jax.lax.pmean(total, batch_axis)
            return total

        shard_map = _compat.shard_map()
        feed_spec = (P(None, batch_axis) if batch_axis else P())
        pipeline_loss = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(axis), {k: feed_spec for k in feed_specs}, P()),
            out_specs=P(),
        )

        lowerer = self.lowerer
        pre_ops, post_ops = self.pre_scalar_ops, self.post_scalar_ops
        tmpl, attrs = self.update_template, dict(self.update_attrs)
        packed_slots, scalar_slots = self.packed_slots, self.scalar_slots
        opdef = op_registry.get_op_def(tmpl.type)
        lr_name = tmpl.input("LearningRate")[0]

        def train_step(packed, accs, scalars, feeds, key):
            env = dict(scalars)
            for op in pre_ops:
                lowerer.lower_op(op, env, key)
            split = {
                k: v.reshape((m, v.shape[0] // m) + v.shape[1:])
                for k, v in feeds.items()
            }

            def loss_fn(p):
                return pipeline_loss(p, split, key)

            loss, grad = jax.value_and_grad(loss_fn)(packed)
            ins = {"Param": [packed], "Grad": [grad],
                   "LearningRate": [jnp.reshape(env[lr_name], (1,))]}
            for slot in packed_slots:
                ins[slot] = [accs[slot]]
            for slot in scalar_slots:
                ins[slot] = [env[tmpl.input(slot)[0]]]
            ctx = LowerContext(
                tmpl, rng=lambda: jax.random.PRNGKey(0), is_test=False,
                block_lowerer=lowerer)
            outs = normalize_outputs(opdef, opdef.lower(ctx, ins, attrs))
            new_packed = outs["ParamOut"][0]
            new_accs = {slot: outs["%sOut" % slot][0]
                        for slot in packed_slots}
            for op in post_ops:
                lowerer.lower_op(op, env, key)
            new_scalars = {n: env[n] for n in scalars}
            return new_packed, new_accs, new_scalars, loss

        row = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())
        feed_in = NamedSharding(mesh, P(batch_axis) if batch_axis else P())
        self.jitted = jax.jit(
            train_step,
            in_shardings=(row, {s: row for s in self.packed_slots},
                          {n: rep for n in self.scalar_state},
                          {n: feed_in for n in feed_specs}, rep),
            out_shardings=(row, {s: row for s in self.packed_slots},
                           {n: rep for n in self.scalar_state}, rep),
            donate_argnums=(0, 1, 2),
        )

    # -- packed state <-> scope --------------------------------------------
    def pack_from_scope(self, scope):
        """Build the packed [S, L] param/acc arrays from scope values."""
        row = NamedSharding(self.mesh, P(self.axis_name))
        rep = NamedSharding(self.mesh, P())

        def read(name):
            v = scope.find_var(name)
            if v is None or v.value is None:
                raise RuntimeError(
                    "pipeline: persistable %r not initialized (run the "
                    "startup program first)" % name)
            return np.asarray(v.value)

        def packed(name_of):
            mat = np.zeros((self.n_stages, self.row_len), np.float32)
            for s, layout in enumerate(self.layouts):
                for pname, off, size, _ in layout:
                    mat[s, off:off + size] = read(
                        name_of(pname)).reshape(-1)
            return jax.device_put(mat, row)

        params = packed(lambda p: p)
        accs = {}
        for slot in self.packed_slots:
            accs[slot] = packed(
                lambda p, slot=slot:
                self.update_by_param[p].input(slot)[0])
        # scalar slots must be equal across params to share one value
        for slot in self.scalar_slots:
            vals = [read(op.input(slot)[0])
                    for op in self.update_by_param.values()]
            if not all(np.allclose(vals[0], v) for v in vals[1:]):
                raise ValueError(
                    "pipeline: per-param %s values diverge; cannot share "
                    "a packed update" % slot)
        scalars = {n: jax.device_put(read(n), rep)
                   for n in self.scalar_state}
        return params, accs, scalars

    def unpack_to_scope(self, scope, params, accs):
        """Write packed params/accs back to their per-name scope vars (for
        save_persistables / inspection)."""
        host = np.asarray(params)
        host_accs = {s: np.asarray(a) for s, a in accs.items()}
        for s, layout in enumerate(self.layouts):
            for pname, off, size, shape in layout:
                scope.set_value(
                    pname, host[s, off:off + size].reshape(shape))
                for slot in self.packed_slots:
                    aname = self.update_by_param[pname].input(slot)[0]
                    scope.set_value(
                        aname,
                        host_accs[slot][s, off:off + size].reshape(shape))
