"""Device mesh construction + sharding policies.

The scaling-book recipe: pick a mesh (axes data/model/pipe), annotate
param/feed shardings with PartitionSpecs, let XLA insert collectives.

Reference-capability map:
  - kAllReduce ReduceStrategy  -> params replicated, batch sharded on
    "data" (grad allreduce inserted by GSPMD);
  - kReduce ReduceStrategy     -> params + opt state sharded over "data"
    (reduce-scatter + all-gather, ZeRO-ish), the reference's
    reduce-then-broadcast round-robin (multi_devices_graph_pass.cc:400-412);
  - DistributeTranspiler pserver sharded tables -> "model"-axis sharding of
    embedding rows (distribute_transpiler.py capability);
  - gen_nccl_id multi-host bootstrap -> jax.distributed.initialize.
"""

import logging
import os

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("paddle_tpu.parallel")


class MeshConfig(object):
    def __init__(self, data=1, model=1, pipe=1, axis_names=("data", "model", "pipe")):
        self.data = data
        self.model = model
        self.pipe = pipe
        self.axis_names = axis_names


def build_mesh(num_devices=None, data=None, model=None, pipe=None,
               devices=None, fsdp=None, tp=None):
    """Build a Mesh; default = pure data-parallel over all local devices.

    Two axis vocabularies:

    * legacy ``("data", "model", "pipe")`` — when ``fsdp``/``tp`` are not
      given; the hand-annotation surface (``sharding_overrides``,
      ``model_sharded_vars``) names these axes.
    * planning ``("data", "fsdp", "tp")`` — when ``fsdp=`` or ``tp=`` is
      given; the axes the sharding transpiler
      (``parallel/sharding.derive_sharding``) derives PartitionSpecs
      over: batch dims shard over ``data x fsdp``, parameters/optimizer
      state shard over ``fsdp`` (ZeRO-ish), Megatron column/row splits
      ride ``tp``. ``data`` defaults to whatever devices remain.
    """
    devices = devices if devices is not None else jax.devices()
    n = num_devices or len(devices)
    devices = devices[:n]
    if fsdp is not None or tp is not None:
        if model not in (None, 1) or pipe not in (None, 1):
            raise ValueError(
                "build_mesh: fsdp/tp axes do not compose with the legacy "
                "model/pipe axes — pick one vocabulary (got model=%r "
                "pipe=%r fsdp=%r tp=%r)" % (model, pipe, fsdp, tp))
        fsdp, tp = int(fsdp or 1), int(tp or 1)
        if data is None:
            data = n // (fsdp * tp)
        arr = np.asarray(devices).reshape(int(data), fsdp, tp)
        mesh = Mesh(arr, ("data", "fsdp", "tp"))
    else:
        model, pipe = int(model or 1), int(pipe or 1)
        if data is None:
            data = n // (model * pipe)
        arr = np.asarray(devices).reshape(int(data), model, pipe)
        mesh = Mesh(arr, ("data", "model", "pipe"))
    record_mesh(mesh)
    return mesh


# one label definition process-wide: per-device series from mesh,
# telemetry and transfer metrics must join on the same key
from paddle_tpu.observability.telemetry import device_label  # noqa: E402


def mesh_device_labels(mesh):
    """Labels of every device in the mesh, flat, mesh order."""
    return [device_label(d) for d in mesh.devices.flat]


def record_mesh(mesh):
    """One gauge series per mesh axis (size), plus the device count —
    the topology half of the per-device observability story. Always on:
    the cost is one gauge write per mesh CONSTRUCTION, never per step."""
    from paddle_tpu.observability.metrics_registry import REGISTRY

    g = REGISTRY.gauge(
        "paddle_tpu_mesh_axis_size",
        "mesh axis sizes of the most recent build_mesh", labels=("axis",))
    for axis, size in mesh.shape.items():
        g.set(int(size), axis=str(axis))
    REGISTRY.gauge(
        "paddle_tpu_mesh_devices",
        "total devices in the most recent build_mesh",
    ).set(int(np.prod(list(mesh.shape.values()))))
    return mesh


def mesh_memory_by_device(mesh):
    """{device label: bytes_in_use} over the mesh's ADDRESSABLE devices
    ({} when the backend doesn't report, e.g. CPU). The per-chip OOM
    lens: a single device trending away from its peers is the canary."""
    out = {}
    for d in mesh.devices.flat:
        if getattr(d, "process_index", 0) != jax.process_index():
            continue
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[device_label(d)] = int(stats.get("bytes_in_use", 0))
    return out


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, heartbeat_timeout_s=None):
    """Multi-host bootstrap — the gen_nccl_id_op.cc:31 equivalent. On a TPU
    pod slice, jax.distributed discovers peers from the TPU runtime; on
    CPU/GPU, pass coordinator address + ranks (PADDLE_TRAINER_* env style).

    heartbeat_timeout_s bounds how long survivors wait before a dead
    peer is declared failed (the ExceptionHolder promptness knob,
    reference framework/details/exception_holder.h); default is jax's
    100s. Overridable via PADDLE_HEARTBEAT_TIMEOUT seconds in env.
    """
    kwargs = {}
    if coordinator_address:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    if heartbeat_timeout_s is None and os.environ.get(
            "PADDLE_HEARTBEAT_TIMEOUT"):
        heartbeat_timeout_s = int(os.environ["PADDLE_HEARTBEAT_TIMEOUT"])
    if heartbeat_timeout_s is not None:
        kwargs["heartbeat_timeout_seconds"] = int(heartbeat_timeout_s)
    jax.distributed.initialize(**kwargs)


class ShardingPolicy(object):
    """Maps var names -> NamedSharding for the CompiledProgram.

    strategy:
      "all_reduce" (default): replicate state, shard feeds on batch.
      "reduce":              shard state on dim 0 when divisible (ZeRO-ish).
    model_sharded_vars: names (e.g. big embedding tables / TP weights) to
      shard on the "model" axis: dim 0 for embeddings, dim -1 otherwise
      would be a per-var choice — a dict name->PartitionSpec overrides.
    """

    def __init__(
        self,
        mesh,
        strategy="all_reduce",
        state_shapes=None,
        model_sharded_vars=None,
        feed_batch_axis=0,
        overrides=None,
    ):
        self.mesh = mesh
        self.strategy = strategy
        self.state_shapes = state_shapes or {}
        self.model_sharded_vars = set(model_sharded_vars or ())
        self.feed_batch_axis = feed_batch_axis
        self.overrides = dict(overrides or {})
        self._logged = set()

    def _note_fallback(self, name, reason):
        """No silent caps: every var that degrades to full replication when a
        sharded layout was plausible is logged once, and tagged in plan()."""
        if name not in self._logged:
            self._logged.add(name)
            logger.info("sharding fallback: %s -> replicated (%s)", name,
                        reason)

    def plan(self):
        """name -> (spec, note) for every known state var (observability)."""
        out = {}
        for name in sorted(self.state_shapes):
            s = self.state_sharding(name)
            out[name] = (str(s.spec), "fallback" if name in self._logged
                         else "")
        return out

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def _spec_to_sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def state_sharding(self, name):
        if name in self.overrides:
            return self._spec_to_sharding(self.overrides[name])
        shape = self.state_shapes.get(name)
        # optimizer accumulators ("<param>_<acc>_<n>") inherit their
        # param's tensor-parallel layout when same-shaped (moments must be
        # partitioned like the weight or GSPMD resharding thrashes);
        # scalar state (beta_pow etc.) falls through to the policies below
        for base, spec in self.overrides.items():
            if (
                name.startswith(base + "_")
                and shape is not None
                and tuple(shape) == tuple(self.state_shapes.get(base, ()))
            ):
                return self._spec_to_sharding(spec)
        missed = []  # why each plausible sharded layout was not taken
        if name in self.model_sharded_vars and shape:
            msize = self.mesh.shape.get("model", 1)
            if msize > 1 and shape[0] % msize == 0:
                return self._spec_to_sharding(
                    P("model", *([None] * (len(shape) - 1)))
                )
            if msize > 1:
                missed.append(
                    "model axis %d does not divide dim0 of %s" % (msize, shape)
                )
        if self.strategy == "reduce" and shape:
            dsize = self.mesh.shape.get("data", 1)
            if len(shape) >= 1 and shape[0] % dsize == 0 and int(
                np.prod(shape)
            ) >= 1024:
                return self._spec_to_sharding(
                    P("data", *([None] * (len(shape) - 1)))
                )
            if len(shape) >= 1 and dsize > 1:
                missed.append(
                    "dim0 of %s not divisible by data axis %d"
                    % (shape, dsize)
                    if shape[0] % dsize
                    else "numel %d < 1024 threshold" % int(np.prod(shape))
                )
        if missed:
            self._note_fallback(name, "; ".join(missed))
        return self.replicated()

    def feed_sharding(self, name, shape=None):
        if name in self.overrides:
            return self._spec_to_sharding(self.overrides[name])
        if shape is not None:
            dsize = self.mesh.shape.get("data", 1)
            if len(shape) == 0 or (dsize > 1 and shape[0] % dsize != 0):
                # Scalar / non-batch feed (fed LR, margin...): replicate.
                self._note_fallback(
                    name,
                    "feed shape %s not batch-shardable over data axis %d"
                    % (tuple(shape), dsize),
                )
                return self.replicated()
        return self._spec_to_sharding(P("data"))
