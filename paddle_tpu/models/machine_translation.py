"""Seq2seq machine-translation model (attention encoder-decoder).

Reference parity: ``benchmark/fluid/models/machine_translation.py``
(seq_to_seq_net: bi-LSTM encoder + simple_attention LSTM decoder) and the
generation path of ``tests/book/test_machine_translation.py`` (beam search).
Dense-padded regime: [batch, max_len] token ids + [batch] lengths replace
LoD packing; the decoder is the fused attention_lstm op (one lax.scan), and
generation is the fused whole-loop beam decoder.
"""

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr

DECODER_NAME = "mt_decoder"
TGT_EMB_NAME = "mt_tgt_emb_table"


def _encoder(src_word_idx, src_len, src_vocab, emb_dim, encoder_size,
             decoder_size):
    src_emb = fluid.layers.embedding(
        input=src_word_idx, size=[src_vocab, emb_dim],
        param_attr=ParamAttr(name="mt_src_emb_table"),
    )
    fwd_proj = fluid.layers.fc(
        input=src_emb, size=encoder_size * 4, num_flatten_dims=2,
        bias_attr=False, param_attr=ParamAttr(name="mt_enc_fwd_proj_w"),
    )
    fwd, _ = fluid.layers.dynamic_lstm(
        input=fwd_proj, size=encoder_size * 4, length=src_len,
        use_peepholes=False, param_attr=ParamAttr(name="mt_enc_fwd_w"),
        bias_attr=ParamAttr(name="mt_enc_fwd_b"),
    )
    rev_proj = fluid.layers.fc(
        input=src_emb, size=encoder_size * 4, num_flatten_dims=2,
        bias_attr=False, param_attr=ParamAttr(name="mt_enc_rev_proj_w"),
    )
    rev, _ = fluid.layers.dynamic_lstm(
        input=rev_proj, size=encoder_size * 4, length=src_len,
        is_reverse=True, use_peepholes=False,
        param_attr=ParamAttr(name="mt_enc_rev_w"),
        bias_attr=ParamAttr(name="mt_enc_rev_b"),
    )
    encoded_vector = fluid.layers.concat([fwd, rev], axis=2)  # [B, S, 2H]
    encoded_proj = fluid.layers.fc(
        input=encoded_vector, size=decoder_size, num_flatten_dims=2,
        bias_attr=False, param_attr=ParamAttr(name="mt_enc_proj_w"),
    )
    # State after the reversed pass over the full sequence seeds the decoder.
    backward_first = fluid.layers.sequence_pool(
        input=rev, pool_type="first"
    )
    decoder_boot = fluid.layers.fc(
        input=backward_first, size=decoder_size, act="tanh", bias_attr=False,
        param_attr=ParamAttr(name="mt_dec_boot_w"),
    )
    return encoded_vector, encoded_proj, decoder_boot


def build(
    src_vocab=1000,
    tgt_vocab=1000,
    src_seq_len=32,
    tgt_seq_len=32,
    emb_dim=64,
    encoder_size=64,
    decoder_size=64,
):
    """Training graph. Feeds: source_sequence [B, Ts] int64, source_length
    [B] int64, target_sequence [B, Tt] int64 (shifted-right, <s> first),
    label [B, Tt] int64, label_mask [B, Tt] float32 (1 on real tokens)."""
    src = fluid.layers.data(
        name="source_sequence", shape=[src_seq_len], dtype="int64"
    )
    src_len = fluid.layers.data(name="source_length", shape=[1],
                                dtype="int64")
    tgt = fluid.layers.data(
        name="target_sequence", shape=[tgt_seq_len], dtype="int64"
    )
    label = fluid.layers.data(name="label", shape=[tgt_seq_len],
                              dtype="int64")
    label_mask = fluid.layers.data(
        name="label_mask", shape=[tgt_seq_len], dtype="float32"
    )

    encoded_vector, encoded_proj, decoder_boot = _encoder(
        src, src_len, src_vocab, emb_dim, encoder_size, decoder_size
    )

    tgt_emb = fluid.layers.embedding(
        input=tgt, size=[tgt_vocab, emb_dim],
        param_attr=ParamAttr(name=TGT_EMB_NAME),
    )
    dec_hidden = fluid.layers.attention_lstm_decoder(
        tgt_emb, encoded_vector, encoded_proj, decoder_boot,
        size=decoder_size, encoder_len=src_len, name=DECODER_NAME,
    )
    logits = fluid.layers.fc(
        input=dec_hidden, size=tgt_vocab, num_flatten_dims=2,
        param_attr=ParamAttr(name=DECODER_NAME + "_out_w"),
        bias_attr=ParamAttr(name=DECODER_NAME + "_out_b"),
    )
    # Per-token CE, masked mean over real tokens.
    flat_logits = fluid.layers.reshape(logits, shape=[-1, tgt_vocab])
    flat_label = fluid.layers.reshape(label, shape=[-1, 1])
    tok_loss = fluid.layers.softmax_with_cross_entropy(
        flat_logits, flat_label
    )
    tok_loss = fluid.layers.reshape(tok_loss, shape=[-1, tgt_seq_len])
    masked = fluid.layers.elementwise_mul(tok_loss, label_mask)
    total = fluid.layers.reduce_sum(masked)
    denom = fluid.layers.reduce_sum(label_mask)
    avg_cost = fluid.layers.elementwise_div(total, denom)
    return avg_cost, [src, src_len, tgt, label, label_mask], {}


def build_generator(
    src_vocab=1000,
    tgt_vocab=1000,
    src_seq_len=32,
    emb_dim=64,
    encoder_size=64,
    decoder_size=64,
    beam_size=4,
    max_len=32,
    start_id=1,
    end_id=2,
):
    """Beam-search generation graph sharing the training weights by name.
    Returns (sentence_ids [B, beam, max_len], scores [B, beam], feeds)."""
    src = fluid.layers.data(
        name="source_sequence", shape=[src_seq_len], dtype="int64"
    )
    src_len = fluid.layers.data(name="source_length", shape=[1],
                                dtype="int64")
    encoded_vector, encoded_proj, decoder_boot = _encoder(
        src, src_len, src_vocab, emb_dim, encoder_size, decoder_size
    )
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("mt_generator")
    tgt_emb_param = helper.create_parameter(
        attr=ParamAttr(name=TGT_EMB_NAME), shape=[tgt_vocab, emb_dim],
        dtype="float32",
    )
    ids, scores = fluid.layers.attention_lstm_beam_decode(
        encoded_vector, encoded_proj, decoder_boot, tgt_emb_param,
        size=decoder_size, vocab_size=tgt_vocab, beam_size=beam_size,
        max_len=max_len, start_id=start_id, end_id=end_id,
        encoder_len=src_len, name=DECODER_NAME,
    )
    return ids, scores, [src, src_len]
