"""MNIST conv model (benchmark/fluid/models/mnist.py parity: two
conv-pool blocks + fc head)."""

import paddle_tpu as fluid


def build(batch_size=None, img_shape=(1, 28, 28), class_num=10, dtype="float32"):
    images = fluid.layers.data(name="pixel", shape=list(img_shape), dtype=dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")

    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=images,
        filter_size=5,
        num_filters=20,
        pool_size=2,
        pool_stride=2,
        act="relu",
    )
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1,
        filter_size=5,
        num_filters=50,
        pool_size=2,
        pool_stride=2,
        act="relu",
    )
    predict = fluid.layers.fc(input=conv_pool_2, size=class_num, act="softmax")
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    return avg_cost, [images, label], {"accuracy": acc, "predict": predict}
