"""Transformer encoder-decoder for machine translation.

Reference parity: the reference's Transformer benchmark model
(``tests/unittests/dist_transformer.py`` / ``benchmark/fluid/models/
machine_translation.py`` attention seq2seq). TPU-first differences:
attention is the fused scaled_dot_product_attention op (Pallas flash on
TPU), sequences are dense-padded [batch, T] with explicit length masks,
and pre-norm residual blocks (better large-scale training stability).
"""

import paddle_tpu as fluid


def _ffn(x, d_model, d_inner, name):
    h = fluid.layers.fc(
        input=x, size=d_inner, num_flatten_dims=2, act="relu",
        name=name + "_fc1",
    )
    return fluid.layers.fc(
        input=h, size=d_model, num_flatten_dims=2, name=name + "_fc2"
    )


def _prenorm(x, name):
    return fluid.layers.layer_norm(
        x, begin_norm_axis=2, name=name + "_ln"
    )


def _residual(x, y, dropout, is_test, name):
    if dropout:
        y = fluid.layers.dropout(y, dropout_prob=dropout, is_test=is_test)
    return fluid.layers.elementwise_add(x, y)


def _self_attention_block(x, mask, n_head, d_model, dropout, is_test, name):
    """Pre-norm self-attention + residual — the shared first half of an
    encoder layer (dense-FFN here, MoE-FFN in switch_transformer)."""
    attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_attn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=mask,
        is_test=is_test,
        name=name + "_mha",
    )
    return _residual(x, attn, dropout, is_test, name + "_res1")


def encoder_layer(x, mask, n_head, d_model, d_inner, dropout, is_test, name):
    x = _self_attention_block(x, mask, n_head, d_model, dropout, is_test,
                              name)
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res2")


def decoder_layer(x, enc_out, cross_mask, n_head, d_model,
                  d_inner, dropout, is_test, name):
    self_attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_sattn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        causal=True,
        is_test=is_test,
        name=name + "_smha",
    )
    x = _residual(x, self_attn, dropout, is_test, name + "_res1")
    cross = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_cattn"), enc_out, enc_out,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=cross_mask,
        is_test=is_test,
        name=name + "_cmha",
    )
    x = _residual(x, cross, dropout, is_test, name + "_res2")
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res3")


def build(
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
    dropout=0.1,
    label_smooth_eps=0.1,
    is_test=False,
):
    """Returns (avg_cost, feeds, extras). Feeds: src_word [B,S], src_len
    [B,1], trg_word [B,T] (decoder input), trg_len [B,1], label [B,T]."""
    src = fluid.layers.data("src_word", shape=[max_length], dtype="int64")
    src_len = fluid.layers.data("src_len", shape=[1], dtype="int64")
    trg = fluid.layers.data("trg_word", shape=[max_length], dtype="int64")
    label = fluid.layers.data("label", shape=[max_length], dtype="int64")

    src_mask = fluid.layers.sequence_mask(
        src_len, maxlen=max_length, dtype="float32"
    )  # [B, S] validity

    # Embeddings + sinusoid position encoding
    src_emb = fluid.layers.embedding(
        input=src, size=[src_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="src_emb"),
    )
    src_emb = fluid.layers.scale(src_emb, scale=d_model ** 0.5)
    enc_in = fluid.layers.add_position_encoding(src_emb)

    trg_emb = fluid.layers.embedding(
        input=trg, size=[trg_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="trg_emb"),
    )
    trg_emb = fluid.layers.scale(trg_emb, scale=d_model ** 0.5)
    dec_in = fluid.layers.add_position_encoding(trg_emb)

    enc = enc_in
    for i in range(n_layer):
        enc = encoder_layer(
            enc, src_mask, n_head, d_model, d_inner, dropout, is_test,
            "enc_%d" % i,
        )
    enc = _prenorm(enc, "enc_final")

    dec = dec_in
    for i in range(n_layer):
        dec = decoder_layer(
            dec, enc, src_mask, n_head, d_model, d_inner, dropout,
            is_test, "dec_%d" % i,
        )
    dec = _prenorm(dec, "dec_final")

    logits = fluid.layers.fc(
        input=dec, size=trg_vocab_size, num_flatten_dims=2,
        name="proj_logits",
    )

    # Smoothed cross entropy in factored form: with q = eps/V + (1-eps)*onehot,
    #   -sum_i q_i * logp_i = (1-eps) * hardCE + (eps/V) * (-sum_i logp_i),
    # algebraically identical to one_hot -> label_smooth -> soft-label CE
    # (the reference benchmark's formulation) but never materializes the
    # [B, T, V] soft-label tensor — at V=32k that tensor costs more HBM
    # traffic than a whole decoder layer. The one_hot/label_smooth ops
    # remain available (and tested) for programs that want explicit
    # soft labels, e.g. distillation targets.
    flat_logits = fluid.layers.reshape(logits, shape=[-1, trg_vocab_size])
    flat_label = fluid.layers.reshape(label, shape=[-1, 1])
    from paddle_tpu import flags as _flags
    if _flags.get("fused_ce"):
        # MFU lever #1 (docs/MFU_PLAN.md): one fused pass, bf16 logits,
        # f32-accumulated reductions, hand-written one-pass backward —
        # algebraically identical to the composed head below
        cost = fluid.layers.fused_label_smooth_ce(
            flat_logits, flat_label, epsilon=label_smooth_eps)
    else:
        cost = fluid.layers.softmax_with_cross_entropy(
            flat_logits, flat_label)
        if label_smooth_eps:
            neg_sum_logp = fluid.layers.scale(
                fluid.layers.reduce_sum(
                    fluid.layers.log_softmax(flat_logits), dim=-1,
                    keep_dim=True
                ),
                scale=-1.0,
            )
            cost = fluid.layers.elementwise_add(
                fluid.layers.scale(cost, scale=1.0 - label_smooth_eps),
                fluid.layers.scale(
                    neg_sum_logp, scale=label_smooth_eps / trg_vocab_size
                ),
            )

    # Mask loss on padded target positions.
    trg_len = fluid.layers.data("trg_len", shape=[1], dtype="int64")
    trg_mask = fluid.layers.sequence_mask(
        trg_len, maxlen=max_length, dtype="float32"
    )
    cost = fluid.layers.reshape(cost, shape=[-1, max_length])
    masked = fluid.layers.elementwise_mul(cost, trg_mask)
    total = fluid.layers.reduce_sum(masked)
    denom = fluid.layers.reduce_sum(trg_mask)
    avg_cost = fluid.layers.elementwise_div(total, denom)

    feeds = [src, src_len, trg, trg_len, label]
    return avg_cost, feeds, {"logits": logits}


def build_inference(train_prog, logits):
    """Derive the generation graph from the TRAINED program: clone with
    is_test flipped (inference dropout) and prune to the logits fetch —
    the loss head, backward and optimizer ops all fall away, so running
    it cannot touch the weights. Parameters bind through the shared
    scope. Used by greedy_generate/beam_generate below."""
    from paddle_tpu import io

    return io.prune_program(
        train_prog.clone(for_test=True),
        ["src_word", "src_len", "trg_word"],
        [logits.name if hasattr(logits, "name") else logits],
    )


def greedy_generate(exe, infer_prog, logits_var, src, src_len,
                    max_length, bos_id=1, eos_id=2):
    """Greedy decode by re-running the full (fixed-shape) decoder over
    the growing prefix — the whole-program-XLA analog of the reference's
    re-score loop; one executable serves every step because shapes never
    change. Returns [B, max_length] int64 (eos-padded)."""
    import numpy as np

    bs = src.shape[0]
    trg = np.full((bs, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    done = np.zeros(bs, bool)
    for t in range(max_length - 1):
        (lg,) = exe.run(
            infer_prog,
            feed={
                "src_word": src,
                "src_len": src_len,
                "trg_word": trg,
            },
            fetch_list=[logits_var],
        )
        nxt = np.asarray(lg)[:, t, :].argmax(-1)
        nxt = np.where(done, eos_id, nxt)
        trg[:, t + 1] = nxt
        done |= nxt == eos_id
        if done.all():
            break
    return trg


def _log_softmax_rows(step):
    """Stable log-softmax over the vocab dim of [N, V] float64 rows."""
    import numpy as np

    mx = step.max(-1, keepdims=True)
    return step - mx - np.log(np.exp(step - mx).sum(-1, keepdims=True))


def _gnmt_penalized_scores(trg_bk, scores, eos_id, len_penalty):
    """GNMT length-penalty division: ``scores / ((5 + len) / 6) ** p``
    over ``[..., K, T]`` hypothesis rows (length = through the first
    eos after bos, or the full budget). float64, broadcast over any
    leading batch dims."""
    import numpy as np

    tail = trg_bk[..., 1:]
    has_eos = (tail == eos_id).any(-1)
    first = (tail == eos_id).argmax(-1)
    lengths = np.where(has_eos, first + 1,
                       trg_bk.shape[-1]).astype(np.float64)
    lp = ((5.0 + lengths) / 6.0) ** float(len_penalty)
    return np.asarray(scores, np.float64) / lp


def _pick_best_beam(trg, pre_scores, bs, K, max_length, eos_id,
                    len_penalty):
    """GNMT length-penalty selection over the final beams."""
    import numpy as np

    trg_bk = trg.reshape(bs, K, max_length)
    best = _gnmt_penalized_scores(
        trg_bk, pre_scores, eos_id, len_penalty).argmax(-1)
    return trg_bk[np.arange(bs), best]


def gnmt_rescore_nbest(tokens, scores, eos_id, len_penalty):
    """Rescore one final beam n-best (``tokens [K, T]`` bos-led rows,
    ``scores [K]`` accumulated log-probs) with the GNMT length penalty
    ``_pick_best_beam`` applies, and reorder score-descending under the
    penalized scores. Returns ``(order [K] int64, tokens[order],
    penalized_scores[order] float32)`` — ``order`` is the permutation of
    the INPUT hypothesis indices, which the wire protocol forwards so a
    streaming client can realign its survivor-chunk replay with the
    rescored ``beam_end``. The sort is stable: ``len_penalty = 0``
    divides by 1 everywhere and returns the identity order."""
    import numpy as np

    tokens = np.asarray(tokens)
    penalized = _gnmt_penalized_scores(tokens, scores, eos_id,
                                       len_penalty)
    order = np.argsort(-penalized, kind="stable").astype(np.int64)
    return order, tokens[order], penalized[order].astype(np.float32)


def beam_generate(exe, infer_prog, logits_var, src, src_len, max_length,
                  beam_size=4, bos_id=1, eos_id=2, len_penalty=0.6):
    """Beam-search decode over the same fixed-shape program: beams ride
    the batch dimension (B*K rows); the per-step selection (incl.
    finished-beam freezing and first-step duplicate suppression) is
    ops/beam_search_ops.beam_step — the same lattice step the in-graph
    beam_search op uses. A GNMT-style length penalty picks the final
    beam. Returns [B, max_length] int64 (best beam per source)."""
    import numpy as np

    from paddle_tpu.ops.beam_search_ops import beam_step

    bs = src.shape[0]
    K = int(beam_size)
    src_k = np.repeat(src, K, axis=0)
    len_k = np.repeat(src_len, K, axis=0)
    trg = np.full((bs * K, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    # int32: beam_step mirrors the dtype, and jnp int64 would
    # warn-and-truncate with x64 disabled
    pre_ids = np.full((bs, K), bos_id, np.int32)
    pre_scores = np.full((bs, K), -1e9, np.float32)
    pre_scores[:, 0] = 0.0  # only beam 0 live at t=0 (no K duplicates)
    rows = np.arange(bs)[:, None]
    for t in range(max_length - 1):
        (lg,) = exe.run(
            infer_prog,
            feed={
                "src_word": src_k,
                "src_len": len_k,
                "trg_word": trg,
            },
            fetch_list=[logits_var],
        )
        step = _log_softmax_rows(
            np.asarray(lg)[:, t, :].astype(np.float64))  # [B*K, V]
        token, sel_scores, parent = beam_step(
            pre_ids, pre_scores, step.reshape(
                bs, K, -1).astype(np.float32), eos_id)
        token = np.asarray(token)
        parent = np.asarray(parent)
        # prefixes follow their beams (the decoder re-reads them)
        trg_bk = trg.reshape(bs, K, max_length)[rows, parent]
        trg_bk[:, :, t + 1] = token
        trg = trg_bk.reshape(bs * K, max_length)
        pre_ids = token
        pre_scores = np.asarray(sel_scores)
        if (token == eos_id).all():
            break
    return _pick_best_beam(trg, pre_scores, bs, K, max_length, eos_id,
                           len_penalty)


def position_encoding_row(t, d_model, dtype="float32"):
    """Host mirror of the add_position_encoding table's row ``t`` —
    fed to the cached decode step (exact same formula as
    ops/attention_ops.py _lower_position_encoding)."""
    import numpy as np

    i = np.arange(d_model // 2, dtype=np.float64)
    angle = float(t) / np.power(10000.0, 2.0 * i / d_model)
    return np.concatenate([np.sin(angle), np.cos(angle)]).astype(
        dtype)[None, :]


def position_encoding_table(max_length, d_model, dtype="float32"):
    """The full [max_length, d_model] sinusoid table, row-exact with
    ``position_encoding_row`` — fed once to the paged decoder's init
    program (and usable anywhere a whole-table mirror is needed)."""
    import numpy as np

    return np.concatenate(
        [position_encoding_row(t, d_model, dtype=dtype)
         for t in range(int(max_length))], axis=0)


def build_cached_decoder(
    batch_size,
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
):
    """Incremental (KV-cached) decoding: O(T) attention per new token
    instead of re-running the decoder over the whole prefix.

    Returns (prepare_prog, step_prog, logits_name). ``prepare_prog``
    runs once per batch: encoder forward, per-layer cross K/V
    projections, src mask, and zeroed self-attention caches — all
    written to persistable scope vars. ``step_prog`` consumes one token
    per run, updates the K/V caches in place via dynamic_update_slice
    (the optimizer-style persistable-state convention), and fetches
    [B, 1, V] logits.

    Build it under the same fresh ``unique_name`` scope as the training
    ``build()`` (both start from empty counters, and every
    param-creating layer here carries the training build's explicit
    name), so parameters bind through the shared scope.
    """
    from paddle_tpu import unique_name

    nn = fluid.layers
    B, T, D = int(batch_size), int(max_length), int(d_model)
    dh = D // n_head

    def heads(x):
        # [B, seq, H*dh] -> [B, H, seq, dh] (seq inferred by reshape)
        return nn.transpose(
            nn.reshape(x, shape=[0, 0, n_head, dh]), perm=[0, 2, 1, 3])

    with unique_name.guard({}):
        prepare = fluid.Program()
        prep_startup = fluid.Program()
        with fluid.program_guard(prepare, prep_startup):
            src = nn.data("src_word", shape=[T], dtype="int64")
            src_len = nn.data("src_len", shape=[1], dtype="int64")
            src_mask = nn.sequence_mask(src_len, maxlen=T, dtype="float32")
            emb = nn.embedding(
                input=src, size=[src_vocab_size, D],
                param_attr=fluid.ParamAttr(name="src_emb"))
            enc = nn.add_position_encoding(nn.scale(emb, scale=D ** 0.5))
            for i in range(n_layer):
                enc = encoder_layer(enc, src_mask, n_head, D, d_inner,
                                    0.0, True, "enc_%d" % i)
            enc = _prenorm(enc, "enc_final")
            blk = prepare.global_block()

            def persist(name, value):
                out = blk.create_var(name=name, shape=None,
                                     dtype="float32", persistable=True)
                nn.assign(value, output=out)

            persist("gen_src_mask", src_mask)
            for i in range(n_layer):
                kc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_k" % i))
                vc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_v" % i))
                persist("gen_kcross_%d" % i, kc)
                persist("gen_vcross_%d" % i, vc)
                zeros = nn.fill_constant([B, n_head, T, dh], "float32",
                                         0.0)
                persist("gen_kcache_%d" % i, zeros)
                persist("gen_vcache_%d" % i, zeros)

        step = fluid.Program()
        step_startup = fluid.Program()
        with fluid.program_guard(step, step_startup):
            blk = step.global_block()
            cur = nn.data("cur_tok", shape=[1], dtype="int64")
            pe_row = nn.data("pe_row", shape=[1, D], dtype="float32")
            pos = nn.data("gen_pos", shape=[1], dtype="int64",
                          append_batch_size=False)
            # cache validity is derived from gen_pos in-graph (positions
            # <= pos), so callers cannot feed an inconsistent length
            cache_mask = nn.expand(
                nn.sequence_mask(
                    fluid.layers.increment(pos, value=1, in_place=False),
                    maxlen=T, dtype="float32"),
                expand_times=[B, 1])

            def pvar(name, shape):
                return blk.create_var(name=name, shape=shape,
                                      dtype="float32", persistable=True)

            src_mask = pvar("gen_src_mask", [B, T])
            emb = nn.embedding(
                input=cur, size=[trg_vocab_size, D],
                param_attr=fluid.ParamAttr(name="trg_emb"))
            # lookup_table squeezes the trailing singleton id dim
            # ([B, 1] ids -> [B, D]); restore the length-1 seq axis
            emb = nn.reshape(emb, shape=[0, 1, D])
            h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5), pe_row)
            for i in range(n_layer):
                name = "dec_%d" % i
                kcache = pvar("gen_kcache_%d" % i, [B, n_head, T, dh])
                vcache = pvar("gen_vcache_%d" % i, [B, n_head, T, dh])
                nx = _prenorm(h, name + "_sattn")
                q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                bias_attr=False, name=name + "_smha_q"))
                k1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_k"))
                v1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_v"))
                kcache = nn.dynamic_update_slice(kcache, k1, pos, axis=2,
                                                 out=kcache)
                vcache = nn.dynamic_update_slice(vcache, v1, pos, axis=2,
                                                 out=vcache)
                att = fluid.layers.scaled_dot_product_attention(
                    q, kcache, vcache, mask=cache_mask,
                    sm_scale=dh ** -0.5)
                att = nn.reshape(nn.transpose(att, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    att, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_smha_o"))
                nx2 = _prenorm(h, name + "_cattn")
                q2 = heads(nn.fc(nx2, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name=name + "_cmha_q"))
                ctx = fluid.layers.scaled_dot_product_attention(
                    q2, pvar("gen_kcross_%d" % i, [B, n_head, T, dh]),
                    pvar("gen_vcross_%d" % i, [B, n_head, T, dh]),
                    mask=src_mask, sm_scale=dh ** -0.5)
                ctx = nn.reshape(nn.transpose(ctx, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    ctx, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_cmha_o"))
                ff = _ffn(_prenorm(h, name + "_ffn"), D, d_inner,
                          name + "_ffn")
                h = nn.elementwise_add(h, ff)
            h = _prenorm(h, "dec_final")
            logits = nn.fc(h, trg_vocab_size, num_flatten_dims=2,
                           name="proj_logits")
    return prepare, step, logits.name


def cached_greedy_generate(exe, prepare_prog, step_prog, logits_name,
                           src, src_len, max_length, d_model,
                           bos_id=1, eos_id=2):
    """Greedy decode through the KV-cached step program: prepare once
    (encoder + cross caches), then one [B, 1] token per step. Matches
    greedy_generate output; cost per step is O(T) attention instead of
    a full-prefix decoder re-run."""
    import numpy as np

    bs = src.shape[0]
    exe.run(prepare_prog, feed={"src_word": src, "src_len": src_len},
            fetch_list=[])
    trg = np.full((bs, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    done = np.zeros(bs, bool)
    for t in range(max_length - 1):
        (lg,) = exe.run(
            step_prog,
            feed={
                "cur_tok": trg[:, t:t + 1],
                "pe_row": np.tile(
                    position_encoding_row(t, d_model)[None], (bs, 1, 1)),
                "gen_pos": np.asarray([t], np.int64),
            },
            fetch_list=[logits_name],
        )
        nxt = np.asarray(lg)[:, 0, :].argmax(-1)
        nxt = np.where(done, eos_id, nxt)
        trg[:, t + 1] = nxt
        done |= nxt == eos_id
        if done.all():
            break
    return trg


def build_cache_reorder(batch_size, max_length, n_layer, n_head, d_model):
    """Companion to build_cached_decoder for beam search: permute every
    self-attention cache's batch rows by a fed index vector (beam
    survivors adopt their parent's cache). Cross caches and masks are
    row-constant across a source's beams, so only the self caches move."""
    nn = fluid.layers
    B, T = int(batch_size), int(max_length)
    dh = d_model // n_head
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        blk = prog.global_block()
        parent = nn.reshape(
            nn.data("beam_parent_rows", shape=[1], dtype="int64"),
            shape=[-1])  # [B, 1] feed -> flat row indices
        for i in range(n_layer):
            for kind in ("k", "v"):
                cache = blk.create_var(
                    name="gen_%scache_%d" % (kind, i),
                    shape=[B, n_head, T, dh], dtype="float32",
                    persistable=True)
                nn.assign(nn.gather(cache, parent), output=cache)
    return prog


def cached_beam_generate(exe, prepare_prog, step_prog, reorder_prog,
                         logits_name, src, src_len, max_length, d_model,
                         beam_size=4, bos_id=1, eos_id=2,
                         len_penalty=0.6):
    """Beam search over the KV-cached step program: beams ride the batch
    dim (B*K rows, so build the cached decoder with
    batch_size=B*beam_size), the per-step selection is
    ops/beam_search_ops.beam_step, and surviving beams adopt their
    parent's caches through the reorder program."""
    import numpy as np

    from paddle_tpu.ops.beam_search_ops import beam_step

    bs = src.shape[0]
    K = int(beam_size)
    src_k = np.repeat(src, K, axis=0)
    len_k = np.repeat(src_len, K, axis=0)
    exe.run(prepare_prog, feed={"src_word": src_k, "src_len": len_k},
            fetch_list=[])
    trg = np.full((bs * K, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    pre_ids = np.full((bs, K), bos_id, np.int32)
    pre_scores = np.full((bs, K), -1e9, np.float32)
    pre_scores[:, 0] = 0.0
    rows = np.arange(bs)[:, None]
    for t in range(max_length - 1):
        (lg,) = exe.run(
            step_prog,
            feed={
                "cur_tok": trg[:, t:t + 1],
                "pe_row": np.tile(
                    position_encoding_row(t, d_model)[None],
                    (bs * K, 1, 1)),
                "gen_pos": np.asarray([t], np.int64),
            },
            fetch_list=[logits_name],
        )
        step = _log_softmax_rows(
            np.asarray(lg)[:, 0, :].astype(np.float64))
        token, sel_scores, parent = beam_step(
            pre_ids, pre_scores,
            step.reshape(bs, K, -1).astype(np.float32), eos_id)
        token = np.asarray(token)
        parent = np.asarray(parent)
        global_rows = (rows * K + parent).reshape(-1).astype(np.int64)
        exe.run(reorder_prog, feed={
            "beam_parent_rows": global_rows[:, None]}, fetch_list=[])
        trg_bk = trg.reshape(bs, K, max_length)[rows, parent]
        trg_bk[:, :, t + 1] = token
        trg = trg_bk.reshape(bs * K, max_length)
        pre_ids = token
        pre_scores = np.asarray(sel_scores)
        if (token == eos_id).all():
            break
    return _pick_best_beam(trg, pre_scores, bs, K, max_length, eos_id,
                           len_penalty)


def _sampler_attrs(sampler):
    """Normalize a sampler spec (None, dict, or an object with
    strategy/temperature/top_k/seed attributes — serving.Sampler) into
    the slot_decode_sample op's attrs."""
    if sampler is None:
        return {"strategy": "greedy", "temperature": 1.0, "top_k": 0,
                "base_seed": 0}
    if isinstance(sampler, dict):
        src = dict(sampler)
    else:
        src = {"strategy": getattr(sampler, "strategy", "greedy"),
               "temperature": getattr(sampler, "temperature", 1.0),
               "top_k": getattr(sampler, "top_k", 0),
               "base_seed": getattr(sampler, "seed",
                                    getattr(sampler, "base_seed", 0))}
    strategy = src.get("strategy", "greedy")
    if strategy not in ("greedy", "temperature", "top_k"):
        raise ValueError(
            "sampler strategy must be greedy/temperature/top_k, got %r"
            % (strategy,))
    if strategy == "top_k" and int(src.get("top_k", 0)) < 1:
        raise ValueError(
            "sampler strategy 'top_k' needs top_k >= 1 — 0 would "
            "silently sample the full vocabulary")
    return {"strategy": strategy,
            "temperature": float(src.get("temperature", 1.0)),
            "top_k": int(src.get("top_k", 0)),
            "base_seed": int(src.get("base_seed", src.get("seed", 0)))}


def build_slot_decoder(
    num_slots,
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
    eos_id=2,
    sampler=None,
):
    """Continuous-batching decode: the KV caches become a SLOT-PAGED
    pool (dim 0 = slot, one in-flight sequence per slot) so admissions
    and completions happen mid-flight while ONE fixed-shape step
    executable advances every active sequence — the ragged-paged-
    attention serving shape, built from this op set.

    Returns ``(init_prog, admit_prog, step_prog, token_name)``:

    * ``init_prog`` (run once): allocates the zeroed cache pools —
      per-layer self K/V ``[num_slots, H, T, dh]``, cross K/V pools,
      and the per-slot source mask ``[num_slots, T]`` (column 0 seeded
      valid so an unoccupied slot's cross-attention row is never fully
      masked — softmax over an all-masked row is NaN bait).
    * ``admit_prog`` (once per admitted sequence): encoder forward for
      ONE sequence (feeds ``src_word [1, T]``, ``src_len [1, 1]``,
      ``slot_idx [1]``), then scatters its cross K/V + mask into the
      slot's pool rows and zeroes the slot's self caches — all via
      ``dynamic_update_slice`` along the slot axis. Fixed shapes, so
      every admission reuses one executable.
    * ``step_prog`` (per token): feeds ``cur_tok [S, 1]``,
      ``pe_row [S, 1, D]``, ``gen_pos [S, 1]`` — PER-SLOT positions,
      unlike ``build_cached_decoder``'s single shared position. Each
      slot's new K/V row lands at ITS position via a one-hot
      select-and-add (bit-exact: written positions get exactly the new
      row, others keep exactly the old bits), and each slot's
      attention validity mask derives from its own position in-graph.
      Token selection (``sampler``: greedy default, or a
      temperature/top-k spec with per-slot PRNG streams keyed on
      ``(base_seed, slot, position)``) runs ON DEVICE — the fetch is
      the ``[S, 1]`` int token ids, never the ``[S, 1, V]`` logits, so
      the host round trip per token is vocab-independent.

    Rows are independent end to end (attention, norms and projections
    are per-slot), so a sequence's tokens do not depend on which other
    slots are live — the parity contract tests/test_serving.py pins
    against the dedicated-batch decoders. Build it under the same
    fresh ``unique_name`` scope as the training ``build()``; parameters
    bind through the shared scope by name. Host-side slot management
    lives in ``serving.generation.SlotDecodeSession``.
    """
    from paddle_tpu import unique_name

    nn = fluid.layers
    S, T, D = int(num_slots), int(max_length), int(d_model)
    dh = D // n_head

    def heads(x):
        return nn.transpose(
            nn.reshape(x, shape=[0, 0, n_head, dh]), perm=[0, 2, 1, 3])

    with unique_name.guard({}):
        init = fluid.Program()
        init_startup = fluid.Program()
        with fluid.program_guard(init, init_startup):
            blk = init.global_block()

            def persist(name, value):
                out = blk.create_var(name=name, shape=None,
                                     dtype="float32", persistable=True)
                nn.assign(value, output=out)

            mask0 = nn.fill_constant([S, T], "float32", 0.0)
            mask0 = nn.dynamic_update_slice(
                mask0, nn.fill_constant([S, 1], "float32", 1.0),
                nn.fill_constant([1], "int64", 0), axis=1)
            persist("gen_src_mask", mask0)
            for i in range(n_layer):
                for kind in ("kcross", "vcross", "kcache", "vcache"):
                    persist("gen_%s_%d" % (kind, i),
                            nn.fill_constant([S, n_head, T, dh],
                                             "float32", 0.0))

        admit = fluid.Program()
        admit_startup = fluid.Program()
        with fluid.program_guard(admit, admit_startup):
            blk = admit.global_block()
            src = nn.data("src_word", shape=[T], dtype="int64")
            src_len = nn.data("src_len", shape=[1], dtype="int64")
            slot = nn.data("slot_idx", shape=[1], dtype="int64",
                           append_batch_size=False)
            src_mask = nn.sequence_mask(src_len, maxlen=T,
                                        dtype="float32")  # [1, T]
            emb = nn.embedding(
                input=src, size=[src_vocab_size, D],
                param_attr=fluid.ParamAttr(name="src_emb"))
            enc = nn.add_position_encoding(nn.scale(emb, scale=D ** 0.5))
            for i in range(n_layer):
                enc = encoder_layer(enc, src_mask, n_head, D, d_inner,
                                    0.0, True, "enc_%d" % i)
            enc = _prenorm(enc, "enc_final")

            def pool(name):
                return blk.create_var(name=name,
                                      shape=[S, n_head, T, dh],
                                      dtype="float32", persistable=True)

            mask_pool = blk.create_var(name="gen_src_mask", shape=[S, T],
                                       dtype="float32", persistable=True)
            nn.dynamic_update_slice(mask_pool, src_mask, slot, axis=0,
                                    out=mask_pool)
            zeros_row = nn.fill_constant([1, n_head, T, dh], "float32",
                                         0.0)
            for i in range(n_layer):
                kc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_k" % i))
                vc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_v" % i))
                for pname, row in (("gen_kcross_%d" % i, kc),
                                   ("gen_vcross_%d" % i, vc),
                                   ("gen_kcache_%d" % i, zeros_row),
                                   ("gen_vcache_%d" % i, zeros_row)):
                    p = pool(pname)
                    nn.dynamic_update_slice(p, row, slot, axis=0, out=p)

        step = fluid.Program()
        step_startup = fluid.Program()
        with fluid.program_guard(step, step_startup):
            blk = step.global_block()
            cur = nn.data("cur_tok", shape=[1], dtype="int64")
            pe_row = nn.data("pe_row", shape=[1, D], dtype="float32")
            pos = nn.data("gen_pos", shape=[1], dtype="int64")  # [S, 1]
            # per-slot validity: positions <= this slot's own pos
            cache_mask = nn.sequence_mask(
                fluid.layers.increment(pos, value=1, in_place=False),
                maxlen=T, dtype="float32")  # [S, T]
            # one-hot of each slot's write position, shaped to select
            # along the cache's T axis: [S, 1, T, 1]
            write_sel = nn.reshape(nn.one_hot(pos, depth=T),
                                   shape=[-1, 1, T, 1])
            keep_sel = nn.scale(write_sel, scale=-1.0, bias=1.0)

            def pvar(name, shape):
                return blk.create_var(name=name, shape=shape,
                                      dtype="float32", persistable=True)

            src_mask = pvar("gen_src_mask", [S, T])
            emb = nn.embedding(
                input=cur, size=[trg_vocab_size, D],
                param_attr=fluid.ParamAttr(name="trg_emb"))
            emb = nn.reshape(emb, shape=[0, 1, D])
            h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5), pe_row)
            for i in range(n_layer):
                name = "dec_%d" % i
                kcache = pvar("gen_kcache_%d" % i, [S, n_head, T, dh])
                vcache = pvar("gen_vcache_%d" % i, [S, n_head, T, dh])
                nx = _prenorm(h, name + "_sattn")
                q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                bias_attr=False, name=name + "_smha_q"))
                k1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_k"))
                v1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_v"))
                # per-slot scatter: row i writes at ITS gen_pos[i]; the
                # select-and-add keeps untouched positions bit-identical
                knew = nn.elementwise_add(
                    nn.elementwise_mul(kcache, keep_sel),
                    nn.elementwise_mul(k1, write_sel))
                vnew = nn.elementwise_add(
                    nn.elementwise_mul(vcache, keep_sel),
                    nn.elementwise_mul(v1, write_sel))
                nn.assign(knew, output=kcache)
                nn.assign(vnew, output=vcache)
                att = fluid.layers.scaled_dot_product_attention(
                    q, knew, vnew, mask=cache_mask, sm_scale=dh ** -0.5)
                att = nn.reshape(nn.transpose(att, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    att, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_smha_o"))
                nx2 = _prenorm(h, name + "_cattn")
                q2 = heads(nn.fc(nx2, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name=name + "_cmha_q"))
                ctx = fluid.layers.scaled_dot_product_attention(
                    q2, pvar("gen_kcross_%d" % i, [S, n_head, T, dh]),
                    pvar("gen_vcross_%d" % i, [S, n_head, T, dh]),
                    mask=src_mask, sm_scale=dh ** -0.5)
                ctx = nn.reshape(nn.transpose(ctx, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    ctx, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_cmha_o"))
                ff = _ffn(_prenorm(h, name + "_ffn"), D, d_inner,
                          name + "_ffn")
                h = nn.elementwise_add(h, ff)
            h = _prenorm(h, "dec_final")
            logits = nn.fc(h, trg_vocab_size, num_flatten_dims=2,
                           name="proj_logits")
            tok, _, _ = fluid.layers.slot_decode_sample(
                logits, pos, eos_id=eos_id, max_length=T,
                **_sampler_attrs(sampler))
    return init, admit, step, tok.name


def build_paged_slot_decoder(
    num_slots,
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
    page_size=8,
    num_pages=None,
    num_groups=None,
    bos_id=1,
    eos_id=2,
    sampler=None,
    beam_width=1,
    speculative=0,
):
    """Block-paged continuous-batching decode: the slot pool's dense
    per-slot self caches (``[S, H, T, dh]``) become a PAGE POOL —
    fixed-size KV pages ``[num_pages, H, page_size, dh]`` shared by
    every slot through a per-slot page-index table — and the step
    program becomes a SELF-CONTAINED loop body (token selection,
    position advance and the next token's embedding input all live on
    device), so ``Executor.run_multi_step(step_prog, steps=K)``
    dispatches K decode tokens per host round trip and fetches
    ``[K, S, 1]`` int ids instead of per-token ``[S, 1, V]`` logits.

    Cross-request KV reuse (PR 12): cross-attention K/V is pooled per
    GROUP — ``[num_groups, H, T, dh]`` rows plus a per-slot
    ``group_of`` index — so N slots decoding sampled continuations of
    one source (``SlotDecodeSession.admit_group``) run ONE encoder
    forward and cost one group's cross HBM instead of N dense rows.
    Self-KV pages are refcount-shared host-side; the programs below
    give the host the on-device levers (join a group without an
    encoder run, chunked-prefill a forced prefix, copy-on-write a
    shared page).

    Returns ``(init_prog, admit_prog, join_prog, prefill_prog,
    table_prog, step_prog, token_name)``:

    * ``init_prog`` (once; feeds ``pe_table [T, D]`` — the host's exact
      ``position_encoding_row`` table, so in-graph rows are bit-equal
      to the dense session's fed rows): allocates the zeroed page
      pools, the GROUP cross K/V pools ``[G, H, T, dh]``, the
      per-group source mask (column 0 seeded valid), ``group_of [S,1]``
      (all slots -> group 0), the page table (all rows -> the reserved
      TRASH page 0, where unoccupied slots' writes land harmlessly),
      and the per-slot loop state ``pgd_tok``/``pgd_pos``/``pgd_done``.
    * ``admit_prog`` (once per admitted SOURCE; feeds ``src_word``,
      ``src_len``, ``slot_idx``, ``group_idx``,
      ``page_row [1, pages_per_slot]`` — the host allocator's page ids
      for this slot, unprovisioned tail entries aliasing the last
      valid page — and ``start_tok``/``start_pos [1, 1]``, bos/0
      without a forced prefix): encoder forward for ONE sequence,
      cross K/V + mask scattered into the GROUP's rows, the slot's
      group id, page-table row and loop state installed
      (tok=start_tok, pos=start_pos, done=0). The self pages are NOT
      zeroed — every position a slot attends over was written by that
      slot (or its fork parent) first, so stale page bits are never
      read.
    * ``join_prog`` (per extra group member; feeds ``slot_idx``,
      ``group_idx``, ``page_row``, ``start_tok``, ``start_pos``):
      registers another slot onto an EXISTING group — no encoder
      forward, no cross write; just group id, table row and loop
      state. This is the fork: the member's table row references the
      parent's pages until copy-on-write splits them.
    * ``prefill_prog`` (per uncached forced prefix; feeds
      ``prefix_word [1, T]``, ``prefix_len``, ``write_from [1, 1]``,
      ``slot_idx``, ``group_idx``): ONE causal decoder forward over
      the whole prefix, cross-attending the group's rows, with each
      layer's K/V scattered into the slot's pages by
      ``paged_kv_prefill`` — only positions in
      ``[write_from, prefix_len - 1)`` are written (a prefix-cache hit
      sets ``write_from`` past the cached pages; pad positions route
      to the trash page), replacing token-by-token prefix stepping
      with one dispatch.
    * copy-on-write dispatches are NOT built here: a fork's first
      write to a shared page runs :func:`build_cow_batch_prog` (the
      bucket-laddered batch program the session builds per rung —
      copies land before any repoint, so shared and prefix-cached page
      bits are immutable; one executable covers a whole step window's
      pairs).
    * ``step_prog`` (K per dispatch, NO feeds): O(page)
      ``paged_kv_write`` at each slot's own position, ragged
      ``paged_attention`` bounded by per-slot lengths (empty pages and
      unoccupied slots are skipped), GROUP-indexed cross attention
      (``grouped_cross_attention`` gathers each slot's group row), and
      ``slot_decode_sample`` (greedy / temperature / top-k per
      ``sampler``; finished slots emit eos and freeze). Fetch
      ``token_name`` for the per-step ``[S, 1]`` sampled ids.
    * ``table_prog`` (feeds ``slot_idx``, ``page_row``): rewrite one
      slot's page-table row — mid-flight page extension before a
      dispatch, and the release/rollback paths' reset to the trash
      page.

    ``beam_width=K`` (K >= 2) builds the BEAM variant: the slots become
    ``S / K`` beam LANES of K aligned hypotheses, the step program runs
    ``slot_beam_search`` instead of the sampler — one ``lax.top_k``
    lattice per lane, the same ``beam_step`` the dense
    ``beam_search`` op uses — and the per-step hypothesis reorder is
    executed IN-GRAPH as a parent gather of the page-table rows (plus
    tok/pos/done/score), so the host's only reorder work is refcount
    rebinds: a pure parent permutation moves ZERO KV bytes. Beam adds
    the ``pgd_score [S, 1]`` accumulated-log-prob state (admit/join
    gain a ``start_score [1, 1]`` feed: 0 for the lane's hypothesis 0,
    -1e9 for the rest — the first-step duplicate suppression the dense
    lattice convention uses), done hypotheses' KV writes are routed to
    the trash page in-graph (a frozen hypothesis must never write a
    page a survivor may share), and the last return value is a dict of
    fetch names — ``{"token", "parent", "score", "logits"}`` — instead
    of the single token name (the session fetches the first three;
    ``logits`` is the offline-lattice test hook).

    ``speculative=K`` (K >= 1, sampler mode only) ALSO builds the
    speculative verify program — the tree-attention dispatch that
    scores the anchor plus K host-drafted tokens in one target forward
    and commits the longest accepted prefix in-graph:

    * ``spec_step_prog`` (feeds ``spec_draft [S, K]`` draft tokens,
      ``spec_parent [S, N]`` tree parents and ``spec_anc [S, N, N]``
      ancestor mask, N = K + 1 with node 0 the anchor): embeds all N
      tree nodes at their LOGICAL positions (``pos + depth``), writes
      every node's K/V into the slot's write pages at storage
      ``pos .. pos + N - 1`` (``paged_spec_kv_write``; done slots
      trash-route), runs ``paged_tree_attention`` (committed prefix +
      ancestor path per node), then ``slot_speculative_accept`` — the
      sequential sampler replayed down the tree, sharing
      ``sample_step_tokens`` + ``slot_lifecycle_advance`` so committed
      streams are bit-identical to the plain step program — and
      finally ``paged_spec_kv_compact`` per layer to gather the
      accepted path's K/V rows into canonical storage positions.
      The return value grows to ``(init, admit, join, prefill, table,
      step, spec_step, fetches)`` with ``fetches = {"token":
      <step tok>, "spec_token_seq": [S, N], "spec_accept_len":
      [S, 1]}`` — the plain ``step_prog`` stays available as the
      ``FLAGS_speculative=off`` oracle.

    Build under the training ``build()``'s fresh ``unique_name`` scope;
    parameters bind by name. All decode state is ``pgd_``-prefixed, so
    a paged and a dense session can coexist in one scope. Host-side
    page/group/cache allocation lives in
    ``serving.generation.SlotDecodeSession`` +
    ``serving.kv_pool``.
    """
    from paddle_tpu import unique_name

    from paddle_tpu.kernels.paged_attention import pages_for

    nn = fluid.layers
    S, T, D = int(num_slots), int(max_length), int(d_model)
    dh = D // n_head
    ps = int(page_size)
    npp = pages_for(T, ps)  # pages per slot at full length
    P = int(num_pages) if num_pages else 1 + S * npp
    G = int(num_groups) if num_groups else S
    K = int(beam_width)
    if K < 1:
        raise ValueError("beam_width must be >= 1, got %d" % K)
    beam = K > 1
    if beam and S % K:
        raise ValueError(
            "beam_width=%d does not tile num_slots=%d into aligned "
            "beam lanes" % (K, S))

    def heads(x):
        return nn.transpose(
            nn.reshape(x, shape=[0, 0, n_head, dh]), perm=[0, 2, 1, 3])

    samp = _sampler_attrs(sampler)
    if beam and samp["strategy"] != "greedy":
        raise ValueError(
            "beam_width > 1 replaces token sampling with the beam "
            "lattice — a stochastic sampler (%r) cannot compose with "
            "it" % (samp["strategy"],))
    n_spec = int(speculative)
    if n_spec < 0:
        raise ValueError("speculative must be >= 0, got %d" % n_spec)
    if n_spec and beam:
        raise ValueError(
            "speculative decode verifies the SAMPLER stream — it does "
            "not compose with beam_width > 1 (the lattice already "
            "scores full hypothesis sets per step)")

    with unique_name.guard({}):
        init = fluid.Program()
        init_startup = fluid.Program()
        with fluid.program_guard(init, init_startup):
            blk = init.global_block()

            def persist(name, value, dtype="float32"):
                out = blk.create_var(name=name, shape=None, dtype=dtype,
                                     persistable=True)
                nn.assign(value, output=out)

            pe = nn.data("pe_table", shape=[T, D], dtype="float32",
                         append_batch_size=False)
            persist("pgd_pe_table", pe)
            mask0 = nn.fill_constant([G, T], "float32", 0.0)
            mask0 = nn.dynamic_update_slice(
                mask0, nn.fill_constant([G, 1], "float32", 1.0),
                nn.fill_constant([1], "int64", 0), axis=1)
            persist("pgd_src_mask", mask0)
            for i in range(n_layer):
                for kind in ("kcross", "vcross"):
                    persist("pgd_%s_%d" % (kind, i),
                            nn.fill_constant([G, n_head, T, dh],
                                             "float32", 0.0))
                for kind in ("kpool", "vpool"):
                    persist("pgd_%s_%d" % (kind, i),
                            nn.fill_constant([P, n_head, ps, dh],
                                             "float32", 0.0))
            persist("pgd_group_of",
                    nn.fill_constant([S, 1], "int64", 0), "int64")
            persist("pgd_table",
                    nn.fill_constant([S, npp], "int64", 0), "int64")
            persist("pgd_pos",
                    nn.fill_constant([S, 1], "int64", 0), "int64")
            persist("pgd_tok",
                    nn.fill_constant([S, 1], "int64", bos_id), "int64")
            persist("pgd_done",
                    nn.fill_constant([S, 1], "int64", 1), "int64")
            if beam:
                persist("pgd_score",
                        nn.fill_constant([S, 1], "float32", 0.0))

        def slot_state_feeds():
            """The feeds admit/join share for one member's registration."""
            slot = nn.data("slot_idx", shape=[1], dtype="int64",
                           append_batch_size=False)
            gidx = nn.data("group_idx", shape=[1], dtype="int64",
                           append_batch_size=False)
            page_row = nn.data("page_row", shape=[npp], dtype="int64")
            start_tok = nn.data("start_tok", shape=[1], dtype="int64")
            start_pos = nn.data("start_pos", shape=[1], dtype="int64")
            if not beam:
                return slot, gidx, page_row, start_tok, start_pos
            # the lane's accumulated log-prob seed: 0 for hypothesis 0,
            # -1e9 for the rest (first-step duplicate suppression)
            start_score = nn.data("start_score", shape=[1],
                                  dtype="float32")
            return (slot, gidx, page_row, start_tok, start_pos,
                    start_score)

        def register_member(blk, slot, gidx, page_row, start_tok,
                            start_pos, start_score=None):
            """Install one slot's group id, table row and loop state."""
            def srow(name, value, dtype="int64"):
                p = blk.create_var(name=name,
                                   shape=[S, npp] if name == "pgd_table"
                                   else [S, 1],
                                   dtype=dtype, persistable=True)
                nn.dynamic_update_slice(p, value, slot, axis=0, out=p)

            srow("pgd_group_of", nn.reshape(gidx, shape=[1, 1]))
            srow("pgd_table", page_row)
            srow("pgd_tok", start_tok)
            srow("pgd_pos", start_pos)
            srow("pgd_done", nn.fill_constant([1, 1], "int64", 0))
            if start_score is not None:
                srow("pgd_score", start_score, "float32")

        admit = fluid.Program()
        admit_startup = fluid.Program()
        with fluid.program_guard(admit, admit_startup):
            blk = admit.global_block()
            src = nn.data("src_word", shape=[T], dtype="int64")
            src_len = nn.data("src_len", shape=[1], dtype="int64")
            member_feeds = slot_state_feeds()
            gidx = member_feeds[1]
            src_mask = nn.sequence_mask(src_len, maxlen=T,
                                        dtype="float32")  # [1, T]
            emb = nn.embedding(
                input=src, size=[src_vocab_size, D],
                param_attr=fluid.ParamAttr(name="src_emb"))
            enc = nn.add_position_encoding(nn.scale(emb, scale=D ** 0.5))
            for i in range(n_layer):
                enc = encoder_layer(enc, src_mask, n_head, D, d_inner,
                                    0.0, True, "enc_%d" % i)
            enc = _prenorm(enc, "enc_final")

            def grow(name, shape, value, dtype="float32"):
                p = blk.create_var(name=name, shape=shape, dtype=dtype,
                                   persistable=True)
                nn.dynamic_update_slice(p, value, gidx, axis=0, out=p)

            grow("pgd_src_mask", [G, T], src_mask)
            for i in range(n_layer):
                kc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_k" % i))
                vc = heads(nn.fc(enc, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name="dec_%d_cmha_v" % i))
                grow("pgd_kcross_%d" % i, [G, n_head, T, dh], kc)
                grow("pgd_vcross_%d" % i, [G, n_head, T, dh], vc)
            register_member(blk, *member_feeds)

        join = fluid.Program()
        join_startup = fluid.Program()
        with fluid.program_guard(join, join_startup):
            blk = join.global_block()
            register_member(blk, *slot_state_feeds())

        prefill = fluid.Program()
        prefill_startup = fluid.Program()
        # the prefill program re-creates the decoder's param-owning
        # layers (norms/fcs) exactly like the step program will; a
        # FRESH name scope gives both the training build's .w_0/.w_1
        # parameter suffixes instead of shifting each other's counters
        with unique_name.guard({}), \
                fluid.program_guard(prefill, prefill_startup):
            blk = prefill.global_block()
            pword = nn.data("prefix_word", shape=[T], dtype="int64")
            plen = nn.data("prefix_len", shape=[1], dtype="int64")
            wfrom = nn.data("write_from", shape=[1], dtype="int64")
            slot = nn.data("slot_idx", shape=[1], dtype="int64",
                           append_batch_size=False)
            gidx = nn.data("group_idx", shape=[1], dtype="int64",
                           append_batch_size=False)

            def pvar(name, shape, dtype="float32"):
                return blk.create_var(name=name, shape=shape, dtype=dtype,
                                      persistable=True)

            row = nn.gather(pvar("pgd_table", [S, npp], "int64"),
                            slot)  # [1, npp]
            mask_row = nn.gather(pvar("pgd_src_mask", [G, T]),
                                 gidx)  # [1, T]
            pe_all = nn.reshape(pvar("pgd_pe_table", [T, D]),
                                shape=[1, T, D])
            emb = nn.embedding(
                input=pword, size=[trg_vocab_size, D],
                param_attr=fluid.ParamAttr(name="trg_emb"))  # [1, T, D]
            h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5), pe_all)
            for i in range(n_layer):
                name = "dec_%d" % i
                kpool = pvar("pgd_kpool_%d" % i, [P, n_head, ps, dh])
                vpool = pvar("pgd_vpool_%d" % i, [P, n_head, ps, dh])
                nx = _prenorm(h, name + "_sattn")
                k1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_k"))
                v1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_v"))
                # every layer's K/V for the whole prefix lands in one op;
                # positions below write_from (prefix-cache hits) and the
                # pad tail route to the trash page
                fluid.layers.paged_kv_prefill(
                    kpool, vpool, k1, v1, row, wfrom, plen)
                if i == n_layer - 1:
                    break  # deeper layers don't exist: the rest of this
                    # block's compute feeds nothing
                q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                bias_attr=False, name=name + "_smha_q"))
                att = fluid.layers.scaled_dot_product_attention(
                    q, k1, v1, causal=True, sm_scale=dh ** -0.5)
                att = nn.reshape(nn.transpose(att, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    att, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_smha_o"))
                nx2 = _prenorm(h, name + "_cattn")
                q2 = heads(nn.fc(nx2, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name=name + "_cmha_q"))
                kc = nn.gather(pvar("pgd_kcross_%d" % i,
                                    [G, n_head, T, dh]), gidx)
                vc = nn.gather(pvar("pgd_vcross_%d" % i,
                                    [G, n_head, T, dh]), gidx)
                ctx = fluid.layers.scaled_dot_product_attention(
                    q2, kc, vc, mask=mask_row, sm_scale=dh ** -0.5)
                ctx = nn.reshape(nn.transpose(ctx, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    ctx, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_cmha_o"))
                ff = _ffn(_prenorm(h, name + "_ffn"), D, d_inner,
                          name + "_ffn")
                h = nn.elementwise_add(h, ff)

        table = fluid.Program()
        table_startup = fluid.Program()
        with fluid.program_guard(table, table_startup):
            blk = table.global_block()
            slot = nn.data("slot_idx", shape=[1], dtype="int64",
                           append_batch_size=False)
            page_row = nn.data("page_row", shape=[npp], dtype="int64")
            t = blk.create_var(name="pgd_table", shape=[S, npp],
                               dtype="int64", persistable=True)
            nn.dynamic_update_slice(t, page_row, slot, axis=0, out=t)

        step = fluid.Program()
        step_startup = fluid.Program()
        with fluid.program_guard(step, step_startup):
            blk = step.global_block()

            def pvar(name, shape, dtype="float32"):
                return blk.create_var(name=name, shape=shape, dtype=dtype,
                                      persistable=True)

            tok = pvar("pgd_tok", [S, 1], "int64")
            pos = pvar("pgd_pos", [S, 1], "int64")
            done = pvar("pgd_done", [S, 1], "int64")
            ptable = pvar("pgd_table", [S, npp], "int64")
            group_of = pvar("pgd_group_of", [S, 1], "int64")
            pe_table = pvar("pgd_pe_table", [T, D])
            src_mask = pvar("pgd_src_mask", [G, T])
            # resident tokens per slot AFTER this step's write: pos + 1
            # for LIVE slots, 0 for done/unoccupied ones — a zero length
            # makes the ragged kernel skip the slot outright (its logits
            # are garbage either way: the sampler forces eos on done
            # slots), so empty slots cost neither FLOPs nor page traffic
            # and the grid accounting models exactly what the step runs
            live_row = nn.elementwise_sub(
                nn.fill_constant([S, 1], "int64", 1), done)
            lengths = nn.elementwise_mul(
                fluid.layers.increment(pos, value=1, in_place=False),
                live_row)
            if beam:
                score = pvar("pgd_score", [S, 1])
                # a DONE hypothesis's KV write routes to the trash
                # page: after a reorder it may share its write page
                # with a survivor (both adopted one parent's rows), and
                # frozen hypotheses are never attended past their last
                # live write — so the masked write is pure hygiene that
                # keeps shared page bits immutable without a COW
                write_table = nn.elementwise_mul(ptable, live_row)
            else:
                # sampler slots COW their write page while live and are
                # released before any sharing can alias a done slot's
                # frozen position — the dense write path is unchanged
                write_table = ptable
            emb = nn.embedding(
                input=tok, size=[trg_vocab_size, D],
                param_attr=fluid.ParamAttr(name="trg_emb"))
            emb = nn.reshape(emb, shape=[0, 1, D])  # [S, 1, D]
            pe_row = nn.reshape(
                nn.gather(pe_table, nn.reshape(pos, shape=[-1])),
                shape=[0, 1, D])
            h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5), pe_row)
            for i in range(n_layer):
                name = "dec_%d" % i
                kpool = pvar("pgd_kpool_%d" % i, [P, n_head, ps, dh])
                vpool = pvar("pgd_vpool_%d" % i, [P, n_head, ps, dh])
                nx = _prenorm(h, name + "_sattn")
                q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                bias_attr=False, name=name + "_smha_q"))
                k1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_k"))
                v1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False, name=name + "_smha_v"))
                kpool, vpool = fluid.layers.paged_kv_write(
                    kpool, vpool, k1, v1, write_table, pos)
                att = fluid.layers.paged_attention(
                    q, kpool, vpool, ptable, lengths,
                    sm_scale=dh ** -0.5)
                att = nn.reshape(nn.transpose(att, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    att, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_smha_o"))
                nx2 = _prenorm(h, name + "_cattn")
                q2 = heads(nn.fc(nx2, dh * n_head, num_flatten_dims=2,
                                 bias_attr=False,
                                 name=name + "_cmha_q"))
                # group-indexed cross attention: each slot's row is its
                # GROUP's — N forked slots read one [H, T, dh] row
                ctx = fluid.layers.grouped_cross_attention(
                    q2, pvar("pgd_kcross_%d" % i, [G, n_head, T, dh]),
                    pvar("pgd_vcross_%d" % i, [G, n_head, T, dh]),
                    group_of, src_mask, sm_scale=dh ** -0.5)
                ctx = nn.reshape(nn.transpose(ctx, perm=[0, 2, 1, 3]),
                                 shape=[0, 0, n_head * dh])
                h = nn.elementwise_add(h, nn.fc(
                    ctx, D, num_flatten_dims=2, bias_attr=False,
                    name=name + "_cmha_o"))
                ff = _ffn(_prenorm(h, name + "_ffn"), D, d_inner,
                          name + "_ffn")
                h = nn.elementwise_add(h, ff)
            h = _prenorm(h, "dec_final")
            logits = nn.fc(h, trg_vocab_size, num_flatten_dims=2,
                           name="proj_logits")
            if beam:
                (tok_new, pos_new, done_new, score_new,
                 parent) = fluid.layers.slot_beam_search(
                    logits, tok, pos, done, score, beam_width=K,
                    eos_id=eos_id, max_length=T)
                # THE zero-copy reorder: each surviving hypothesis
                # adopts its parent's page-table ROW in-graph (the op
                # already parent-gathered pos/done and selected the
                # survivor's token/score), so the device-side cost of a
                # hypothesis reshuffle is an [S, npp] int gather — the
                # host only rebinds refcounts, and COW fires later only
                # if a duplicated parent's WRITE page gets written
                nn.assign(nn.gather(ptable,
                                    nn.reshape(parent, shape=[-1])),
                          output=ptable)
                nn.assign(score_new, output=score)
            else:
                tok_new, pos_new, done_new = \
                    fluid.layers.slot_decode_sample(
                        logits, pos, done=done, eos_id=eos_id,
                        max_length=T, **samp)
            # thread the loop state: the NEXT scan iteration embeds the
            # token sampled here, no host in the loop
            nn.assign(tok_new, output=tok)
            nn.assign(pos_new, output=pos)
            nn.assign(done_new, output=done)

        if n_spec:
            Nn = n_spec + 1
            spec = fluid.Program()
            spec_startup = fluid.Program()
            # like prefill: the spec program re-creates the decoder's
            # param-owning layers, so a FRESH name scope keeps the
            # .w_0/.w_1 parameter suffixes aligned with the training
            # build instead of shifting the outer scope's counters
            with unique_name.guard({}), \
                    fluid.program_guard(spec, spec_startup):
                blk = spec.global_block()

                def pvar(name, shape, dtype="float32"):
                    return blk.create_var(name=name, shape=shape,
                                          dtype=dtype, persistable=True)

                # concrete shapes (no -1 batch dim): the slot axis is
                # fixed at S, and shape inference downstream (concat
                # with [S, 1] vars, broadcasts against [S, 1] pos)
                # needs it static
                draft = nn.data("spec_draft", shape=[S, n_spec],
                                dtype="int64",
                                append_batch_size=False)  # [S, K]
                par = nn.data("spec_parent", shape=[S, Nn],
                              dtype="int64",
                              append_batch_size=False)    # [S, N]
                anc = nn.data("spec_anc", shape=[S, Nn, Nn],
                              dtype="int64",
                              append_batch_size=False)    # [S, N, N]
                tok = pvar("pgd_tok", [S, 1], "int64")
                pos = pvar("pgd_pos", [S, 1], "int64")
                done = pvar("pgd_done", [S, 1], "int64")
                ptable = pvar("pgd_table", [S, npp], "int64")
                group_of = pvar("pgd_group_of", [S, 1], "int64")
                pe_table = pvar("pgd_pe_table", [T, D])
                src_mask = pvar("pgd_src_mask", [G, T])
                live_row = nn.elementwise_sub(
                    nn.fill_constant([S, 1], "int64", 1), done)
                # the tree kernel's ragged bound: committed storage for
                # a LIVE slot is [0, pos) and its tree occupies storage
                # pos .. pos + N - 1; -1 marks a dead slot (zero output
                # rows, no pages scanned)
                base = nn.elementwise_sub(
                    nn.elementwise_mul(
                        fluid.layers.increment(pos, value=1,
                                               in_place=False),
                        live_row),
                    nn.fill_constant([S, 1], "int64", 1))
                # a done slot's whole tree writes to the trash page
                write_table = nn.elementwise_mul(ptable, live_row)
                nodes_tok = nn.concat([tok, draft], axis=1)  # [S, N]
                # depth of node i = |ancestors| - 1 (anc carries the
                # diagonal and the anchor column), so its LOGICAL
                # sequence position is pos + depth — clamped into the
                # PE table exactly like the sequential position clamp
                depth = nn.elementwise_sub(
                    nn.reduce_sum(anc, dim=2),               # [S, N]
                    nn.fill_constant([1, 1], "int64", 1))
                logical = nn.elementwise_min(
                    nn.elementwise_add(pos, depth),
                    nn.fill_constant([1, 1], "int64", T - 1))
                pe_rows = nn.reshape(
                    nn.gather(pe_table,
                              nn.reshape(logical, shape=[-1])),
                    shape=[S, Nn, D])
                emb = nn.embedding(
                    input=nodes_tok, size=[trg_vocab_size, D],
                    param_attr=fluid.ParamAttr(name="trg_emb"))
                h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5),
                                       pe_rows)
                spec_pools = []
                for i in range(n_layer):
                    name = "dec_%d" % i
                    kpool = pvar("pgd_kpool_%d" % i,
                                 [P, n_head, ps, dh])
                    vpool = pvar("pgd_vpool_%d" % i,
                                 [P, n_head, ps, dh])
                    nx = _prenorm(h, name + "_sattn")
                    q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                                    bias_attr=False,
                                    name=name + "_smha_q"))
                    k1 = heads(nn.fc(nx, dh * n_head,
                                     num_flatten_dims=2,
                                     bias_attr=False,
                                     name=name + "_smha_k"))
                    v1 = heads(nn.fc(nx, dh * n_head,
                                     num_flatten_dims=2,
                                     bias_attr=False,
                                     name=name + "_smha_v"))
                    kpool, vpool = fluid.layers.paged_spec_kv_write(
                        kpool, vpool, k1, v1, write_table, pos)
                    spec_pools.append((kpool, vpool))
                    att = fluid.layers.paged_tree_attention(
                        q, kpool, vpool, ptable, base, anc,
                        sm_scale=dh ** -0.5, max_length=T)
                    att = nn.reshape(
                        nn.transpose(att, perm=[0, 2, 1, 3]),
                        shape=[0, 0, n_head * dh])
                    h = nn.elementwise_add(h, nn.fc(
                        att, D, num_flatten_dims=2, bias_attr=False,
                        name=name + "_smha_o"))
                    nx2 = _prenorm(h, name + "_cattn")
                    q2 = heads(nn.fc(nx2, dh * n_head,
                                     num_flatten_dims=2,
                                     bias_attr=False,
                                     name=name + "_cmha_q"))
                    ctx = fluid.layers.grouped_cross_attention(
                        q2,
                        pvar("pgd_kcross_%d" % i, [G, n_head, T, dh]),
                        pvar("pgd_vcross_%d" % i, [G, n_head, T, dh]),
                        group_of, src_mask, sm_scale=dh ** -0.5)
                    ctx = nn.reshape(
                        nn.transpose(ctx, perm=[0, 2, 1, 3]),
                        shape=[0, 0, n_head * dh])
                    h = nn.elementwise_add(h, nn.fc(
                        ctx, D, num_flatten_dims=2, bias_attr=False,
                        name=name + "_cmha_o"))
                    ff = _ffn(_prenorm(h, name + "_ffn"), D, d_inner,
                              name + "_ffn")
                    h = nn.elementwise_add(h, ff)
                h = _prenorm(h, "dec_final")
                spec_logits = nn.fc(h, trg_vocab_size,
                                    num_flatten_dims=2,
                                    name="proj_logits")  # [S, N, V]
                (spec_anchor, spec_seq, spec_acc, spec_path, spec_pos,
                 spec_done) = fluid.layers.slot_speculative_accept(
                    spec_logits, nodes_tok, par, pos, done,
                    eos_id=eos_id, max_length=T, **samp)
                # survivor commit AFTER the walk (attention read the
                # pre-commit tree layout) and BEFORE the state assigns
                for kpool, vpool in spec_pools:
                    fluid.layers.paged_spec_kv_compact(
                        kpool, vpool, write_table, pos, spec_path,
                        spec_acc)
                nn.assign(spec_anchor, output=tok)
                nn.assign(spec_pos, output=pos)
                nn.assign(spec_done, output=done)
    if beam:
        fetches = {"token": tok_new.name, "parent": parent.name,
                   "score": score_new.name, "logits": logits.name}
        return init, admit, join, prefill, table, step, fetches
    if n_spec:
        fetches = {"token": tok_new.name,
                   "spec_token_seq": spec_seq.name,
                   "spec_accept_len": spec_acc.name}
        return init, admit, join, prefill, table, step, spec, fetches
    return init, admit, join, prefill, table, step, tok_new.name


def build_draft_decoder(
    num_slots,
    trg_vocab_size=1000,
    max_length=64,
    n_head=4,
    d_model=128,
    d_inner=None,
    page_size=8,
    num_pages=None,
    eos_id=2,
):
    """The small DRAFT transformer for speculative decoding: a 1-layer
    decoder-only LM (no cross attention — cheapness is the point) that
    shares the target's token embedding (``trg_emb``) and position
    table (``pgd_pe_table``) and runs over the SAME paged geometry —
    its own K/V pools ``pgd_draft_{k,v}pool_0 [P, H, ps, dh]`` indexed
    through the target's ``pgd_table`` row per slot, so draft cache
    residency exactly tracks slot page residency with zero extra
    bookkeeping.

    Host-driven single-token steps: ``step_prog`` feeds
    ``draft_tok``/``draft_pos``/``draft_live`` ``[S, 1]`` and fetches
    the greedy next token ``[S, 1]`` (non-live rows write to the trash
    page, attend nothing and emit eos). The serving drafter replays
    each slot's committed tokens through this program to keep the
    draft cache current, then rolls K draft steps ahead of the anchor.

    Correctness is structurally independent of this model: the accept
    walk re-samples every committed token from TARGET logits, so a
    stale or even randomly-initialised draft (its ``draft_dec_*`` /
    ``draft_proj_logits`` params are NOT part of the target training
    build) only lowers the acceptance rate. For the same reason the
    draft pools deliberately sit OUTSIDE copy-on-write: after a fork
    repoints a page, the fork's draft rows for that page are garbage
    until rewritten — harmless, never target-visible.

    Returns ``(init_prog, step_prog, step_startup_prog, token_name)``;
    ``init_prog`` zero-allocates the draft pools and must run after the
    paged decoder's ``init_prog`` (it reuses the session scope).
    ``step_startup_prog`` carries the initializers for EVERY param the
    step program touches — including the shared ``trg_emb`` — so a
    session must run it selectively (only vars the scope is missing),
    the way ``serving.speculative.DraftModelDrafter`` does.
    """
    from paddle_tpu import unique_name

    from paddle_tpu.kernels.paged_attention import pages_for

    nn = fluid.layers
    S, T, D = int(num_slots), int(max_length), int(d_model)
    dh = D // int(n_head)
    ps = int(page_size)
    npp = pages_for(T, ps)
    P = int(num_pages) if num_pages else 1 + S * npp
    di = int(d_inner) if d_inner else 2 * D

    def heads(x):
        return nn.transpose(
            nn.reshape(x, shape=[0, 0, n_head, dh]), perm=[0, 2, 1, 3])

    with unique_name.guard({}):
        init = fluid.Program()
        init_startup = fluid.Program()
        with fluid.program_guard(init, init_startup):
            blk = init.global_block()
            for kind in ("kpool", "vpool"):
                out = blk.create_var(name="pgd_draft_%s_0" % kind,
                                     shape=None, dtype="float32",
                                     persistable=True)
                nn.assign(nn.fill_constant([P, n_head, ps, dh],
                                           "float32", 0.0), output=out)

        step = fluid.Program()
        step_startup = fluid.Program()
        with fluid.program_guard(step, step_startup):
            blk = step.global_block()

            def pvar(name, shape, dtype="float32"):
                return blk.create_var(name=name, shape=shape,
                                      dtype=dtype, persistable=True)

            dtok = nn.data("draft_tok", shape=[S, 1], dtype="int64",
                           append_batch_size=False)
            dpos = nn.data("draft_pos", shape=[S, 1], dtype="int64",
                           append_batch_size=False)
            dlive = nn.data("draft_live", shape=[S, 1], dtype="int64",
                            append_batch_size=False)
            ptable = pvar("pgd_table", [S, npp], "int64")
            pe_table = pvar("pgd_pe_table", [T, D])
            kpool = pvar("pgd_draft_kpool_0", [P, n_head, ps, dh])
            vpool = pvar("pgd_draft_vpool_0", [P, n_head, ps, dh])
            ddone = nn.elementwise_sub(
                nn.fill_constant([S, 1], "int64", 1), dlive)
            lengths = nn.elementwise_mul(
                fluid.layers.increment(dpos, value=1, in_place=False),
                dlive)
            write_table = nn.elementwise_mul(ptable, dlive)
            emb = nn.embedding(
                input=dtok, size=[trg_vocab_size, D],
                param_attr=fluid.ParamAttr(name="trg_emb"))
            emb = nn.reshape(emb, shape=[0, 1, D])
            pe_row = nn.reshape(
                nn.gather(pe_table, nn.reshape(dpos, shape=[-1])),
                shape=[0, 1, D])
            h = nn.elementwise_add(nn.scale(emb, scale=D ** 0.5),
                                   pe_row)
            nx = _prenorm(h, "draft_dec_sattn")
            q = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                            bias_attr=False, name="draft_dec_smha_q"))
            k1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                             bias_attr=False, name="draft_dec_smha_k"))
            v1 = heads(nn.fc(nx, dh * n_head, num_flatten_dims=2,
                             bias_attr=False, name="draft_dec_smha_v"))
            kpool, vpool = fluid.layers.paged_kv_write(
                kpool, vpool, k1, v1, write_table, dpos)
            att = fluid.layers.paged_attention(
                q, kpool, vpool, ptable, lengths, sm_scale=dh ** -0.5)
            att = nn.reshape(nn.transpose(att, perm=[0, 2, 1, 3]),
                             shape=[0, 0, n_head * dh])
            h = nn.elementwise_add(h, nn.fc(
                att, D, num_flatten_dims=2, bias_attr=False,
                name="draft_dec_smha_o"))
            ff = _ffn(_prenorm(h, "draft_dec_ffn"), D, di,
                      "draft_dec_ffn")
            h = nn.elementwise_add(h, ff)
            h = _prenorm(h, "draft_final")
            logits = nn.fc(h, trg_vocab_size, num_flatten_dims=2,
                           name="draft_proj_logits")
            dtok_new, _dpos_new, _ddone_new = \
                fluid.layers.slot_decode_sample(
                    logits, dpos, done=ddone, eos_id=eos_id,
                    max_length=T)
    return init, step, step_startup, dtok_new.name


def build_cow_batch_prog(num_slots, max_length, n_layer, n_head,
                         d_model, page_size, num_pages, pairs):
    """One COALESCED copy-on-write dispatch: copy ``pairs`` KV page
    pairs across every layer's pools and install the affected slots'
    repointed table rows — all in ONE executable, where the per-pair
    ``copy_prog`` would cost ``pairs`` dispatches (beam reorders
    multiply COW pairs per step, so the dispatch count is the hot-path
    number; tests pin it).

    Feeds: ``src_pages``/``dst_pages``/``slot_idxs`` ``[pairs]`` int64
    and ``page_rows [pairs, npp]`` — each pair's slot with that slot's
    FINAL row (a slot with several pairs in one window repeats its
    final row; the repeated scatter is idempotent). Pad short windows
    with ``(src=0, dst=0)`` trash-page self-copies bound to a live
    slot's unchanged row — bit-neutral by construction. Copies all run
    before any repoint (the copy-before-repoint COW discipline, batch
    edition). ``pairs`` is a bucket-ladder rung
    (``analysis.lint.suggest_buckets`` discipline): the session builds
    one program per rung and pads up, so the executable set stays
    finite and warm. Built under a fresh ``unique_name`` scope so the
    structural fingerprint is identical whenever the geometry is —
    rung programs are content-addressed across sessions."""
    from paddle_tpu import unique_name

    from paddle_tpu.kernels.paged_attention import pages_for

    nn = fluid.layers
    S, T = int(num_slots), int(max_length)
    dh = int(d_model) // int(n_head)
    ps = int(page_size)
    npp = pages_for(T, ps)
    P = int(num_pages)
    n = int(pairs)
    if n < 1:
        raise ValueError("build_cow_batch_prog needs pairs >= 1")
    with unique_name.guard({}):
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            blk = prog.global_block()
            src_pages = nn.data("src_pages", shape=[n], dtype="int64",
                                append_batch_size=False)
            dst_pages = nn.data("dst_pages", shape=[n], dtype="int64",
                                append_batch_size=False)
            slot_idxs = nn.data("slot_idxs", shape=[n], dtype="int64",
                                append_batch_size=False)
            page_rows = nn.data("page_rows", shape=[n, npp],
                                dtype="int64", append_batch_size=False)
            idxs = [nn.fill_constant([1], "int64", i) for i in range(n)]
            for i in range(n_layer):
                kpool = blk.create_var(name="pgd_kpool_%d" % i,
                                       shape=[P, n_head, ps, dh],
                                       dtype="float32", persistable=True)
                vpool = blk.create_var(name="pgd_vpool_%d" % i,
                                       shape=[P, n_head, ps, dh],
                                       dtype="float32", persistable=True)
                for j in range(n):
                    fluid.layers.paged_copy_page(
                        kpool, vpool,
                        nn.gather(src_pages, idxs[j]),
                        nn.gather(dst_pages, idxs[j]))
            t = blk.create_var(name="pgd_table", shape=[S, npp],
                               dtype="int64", persistable=True)
            for j in range(n):
                nn.dynamic_update_slice(
                    t, nn.gather(page_rows, idxs[j]),
                    nn.gather(slot_idxs, idxs[j]), axis=0, out=t)
    return prog


def save_compiled_generator(dirname, batch_size, src_vocab_size,
                            trg_vocab_size, max_length, n_layer, n_head,
                            d_model, d_inner, scope=None, bos_id=1,
                            eos_id=2, platforms=None):
    """AOT artifact for GENERATION serving (the level users deploy):
    the entire KV-cached greedy decode — encoder prepare plus a
    lax.scan over the cached step, caches as loop carry — compiled into
    ONE XLA executable with the trained parameters baked in as
    constants. Written in io.save_compiled_inference_model's on-disk
    format, so io.load_compiled_inference_model (and the C++
    ptpu_aot_generator main) serve it with no program IR, no parameter
    files, no per-token host round trip and no tracing at serve time.

    Feeds: src_word int32 [B, max_length], src_len int32 [B, 1].
    Fetch: generated_tokens int32 [B, max_length] — the exact token
    stream cached_greedy_generate produces (pinned by
    tests/test_aot_generation.py against the committed generation
    golden). Reference anchor: inference/api/api_impl.cc serving +
    RecurrentGradientMachine's generation role (SURVEY §2.8), fused
    into one compiled program the TPU way.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.lowering import BlockLowerer, build_step_fn
    from paddle_tpu.executor import Executor, global_scope
    from paddle_tpu.io import _write_compiled_artifact

    scope = scope or global_scope()
    prepare, step, logits_name = build_cached_decoder(
        batch_size, src_vocab_size, trg_vocab_size, max_length,
        n_layer, n_head, d_model, d_inner)
    B, T, D = int(batch_size), int(max_length), int(d_model)
    # kernel lowering is platform-keyed (same invariant
    # save_compiled_inference_model enforces): one artifact per platform
    if platforms is not None and len(platforms) > 1:
        raise ValueError(
            "save_compiled_generator: kernel lowering is platform-keyed; "
            "export one artifact per platform instead of %r"
            % (platforms,))
    platform = (list(platforms)[0] if platforms
                else jax.default_backend())

    gen_names = {"gen_src_mask"}
    for i in range(n_layer):
        for kind in ("kcross", "vcross", "kcache", "vcache"):
            gen_names.add("gen_%s_%d" % (kind, i))
    cache_names = {n for n in gen_names if "cache" in n}

    scope_names = Executor._scope_names(scope)  # walks parent scopes
    prep_lower = BlockLowerer(prepare, 0, is_test=True)
    p_in, p_out = prep_lower.analyze(scope_names,
                                     {"src_word", "src_len"})
    prep_fn = build_step_fn(prepare, ["src_word", "src_len"], [], p_in,
                            p_out, is_test=True, platform=platform)
    # the step program reads the gen_* vars prepare wrote: analyze with
    # them present, exactly as the scope looks after a prepare run
    step_lower = BlockLowerer(step, 0, is_test=True)
    s_in, s_out = step_lower.analyze(
        scope_names | gen_names, {"cur_tok", "pe_row", "gen_pos"})
    step_fn = build_step_fn(step, ["cur_tok", "pe_row", "gen_pos"],
                            [logits_name], s_in, s_out, is_test=True,
                            platform=platform)

    params = {}
    for n in sorted(set(p_in) | (set(s_in) - gen_names)):
        val = scope.get_value(n)
        if val is None:
            raise RuntimeError(
                "save_compiled_generator: parameter %r not in scope "
                "(train or load params first)" % n)
        params[n] = jnp.asarray(val)

    pe_table = jnp.asarray(position_encoding_table(T, D))

    def generate(src_word, src_len):
        key = jax.random.PRNGKey(0)
        prep_state, _ = prep_fn(
            dict(params), {"src_word": src_word, "src_len": src_len},
            key)
        frozen = dict(params)
        caches0 = {}
        for n in gen_names:
            (caches0 if n in cache_names else frozen)[n] = prep_state[n]
        trg0 = jnp.full((B, T), eos_id, jnp.int32).at[:, 0].set(bos_id)
        done0 = jnp.zeros((B,), jnp.bool_)

        def body(carry, t):
            caches, trg, done = carry
            state = dict(frozen)
            state.update(caches)
            cur = jax.lax.dynamic_slice(trg, (0, t), (B, 1))
            pe = jax.lax.dynamic_slice(pe_table, (t, 0), (1, D))
            pe = jnp.broadcast_to(pe[None], (B, 1, D))
            new_state, fetches = step_fn(
                state,
                {"cur_tok": cur, "pe_row": pe,
                 "gen_pos": jnp.reshape(t, (1,))},
                key)
            nxt = jnp.argmax(fetches[0][:, 0, :], axis=-1)
            nxt = nxt.astype(jnp.int32)
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            trg = jax.lax.dynamic_update_slice(trg, nxt[:, None],
                                               (0, t + 1))
            done = done | (nxt == eos_id)
            caches = {n: new_state[n] for n in caches}
            return (caches, trg, done), None

        (_, trg, _), _ = jax.lax.scan(
            body, (caches0, trg0, done0),
            jnp.arange(T - 1, dtype=jnp.int32))
        # tuple, not bare array: CompiledInferenceModel.run iterates
        # the call result as the fetch list
        return (trg,)

    specs = (jax.ShapeDtypeStruct((B, T), jnp.int32),
             jax.ShapeDtypeStruct((B, 1), jnp.int32))
    kwargs = {"platforms": list(platforms)} if platforms else {}
    exported = jax.export.export(jax.jit(generate), **kwargs)(*specs)
    _write_compiled_artifact(
        dirname, exported, ["src_word", "src_len"],
        {"src_word": ((B, T), "int32"), "src_len": ((B, 1), "int32")},
        ["generated_tokens"])
    return logits_name
