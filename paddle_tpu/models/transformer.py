"""Transformer encoder-decoder for machine translation.

Reference parity: the reference's Transformer benchmark model
(``tests/unittests/dist_transformer.py`` / ``benchmark/fluid/models/
machine_translation.py`` attention seq2seq). TPU-first differences:
attention is the fused scaled_dot_product_attention op (Pallas flash on
TPU), sequences are dense-padded [batch, T] with explicit length masks,
and pre-norm residual blocks (better large-scale training stability).
"""

import paddle_tpu as fluid


def _ffn(x, d_model, d_inner, name):
    h = fluid.layers.fc(
        input=x, size=d_inner, num_flatten_dims=2, act="relu",
        name=name + "_fc1",
    )
    return fluid.layers.fc(
        input=h, size=d_model, num_flatten_dims=2, name=name + "_fc2"
    )


def _prenorm(x, name):
    return fluid.layers.layer_norm(
        x, begin_norm_axis=2, name=name + "_ln"
    )


def _residual(x, y, dropout, is_test, name):
    if dropout:
        y = fluid.layers.dropout(y, dropout_prob=dropout, is_test=is_test)
    return fluid.layers.elementwise_add(x, y)


def _self_attention_block(x, mask, n_head, d_model, dropout, is_test, name):
    """Pre-norm self-attention + residual — the shared first half of an
    encoder layer (dense-FFN here, MoE-FFN in switch_transformer)."""
    attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_attn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=mask,
        is_test=is_test,
        name=name + "_mha",
    )
    return _residual(x, attn, dropout, is_test, name + "_res1")


def encoder_layer(x, mask, n_head, d_model, d_inner, dropout, is_test, name):
    x = _self_attention_block(x, mask, n_head, d_model, dropout, is_test,
                              name)
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res2")


def decoder_layer(x, enc_out, cross_mask, n_head, d_model,
                  d_inner, dropout, is_test, name):
    self_attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_sattn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        causal=True,
        is_test=is_test,
        name=name + "_smha",
    )
    x = _residual(x, self_attn, dropout, is_test, name + "_res1")
    cross = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_cattn"), enc_out, enc_out,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=cross_mask,
        is_test=is_test,
        name=name + "_cmha",
    )
    x = _residual(x, cross, dropout, is_test, name + "_res2")
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res3")


def build(
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
    dropout=0.1,
    label_smooth_eps=0.1,
    is_test=False,
):
    """Returns (avg_cost, feeds, extras). Feeds: src_word [B,S], src_len
    [B,1], trg_word [B,T] (decoder input), trg_len [B,1], label [B,T]."""
    src = fluid.layers.data("src_word", shape=[max_length], dtype="int64")
    src_len = fluid.layers.data("src_len", shape=[1], dtype="int64")
    trg = fluid.layers.data("trg_word", shape=[max_length], dtype="int64")
    label = fluid.layers.data("label", shape=[max_length], dtype="int64")

    src_mask = fluid.layers.sequence_mask(
        src_len, maxlen=max_length, dtype="float32"
    )  # [B, S] validity

    # Embeddings + sinusoid position encoding
    src_emb = fluid.layers.embedding(
        input=src, size=[src_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="src_emb"),
    )
    src_emb = fluid.layers.scale(src_emb, scale=d_model ** 0.5)
    enc_in = fluid.layers.add_position_encoding(src_emb)

    trg_emb = fluid.layers.embedding(
        input=trg, size=[trg_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="trg_emb"),
    )
    trg_emb = fluid.layers.scale(trg_emb, scale=d_model ** 0.5)
    dec_in = fluid.layers.add_position_encoding(trg_emb)

    enc = enc_in
    for i in range(n_layer):
        enc = encoder_layer(
            enc, src_mask, n_head, d_model, d_inner, dropout, is_test,
            "enc_%d" % i,
        )
    enc = _prenorm(enc, "enc_final")

    dec = dec_in
    for i in range(n_layer):
        dec = decoder_layer(
            dec, enc, src_mask, n_head, d_model, d_inner, dropout,
            is_test, "dec_%d" % i,
        )
    dec = _prenorm(dec, "dec_final")

    logits = fluid.layers.fc(
        input=dec, size=trg_vocab_size, num_flatten_dims=2,
        name="proj_logits",
    )

    # Smoothed cross entropy in factored form: with q = eps/V + (1-eps)*onehot,
    #   -sum_i q_i * logp_i = (1-eps) * hardCE + (eps/V) * (-sum_i logp_i),
    # algebraically identical to one_hot -> label_smooth -> soft-label CE
    # (the reference benchmark's formulation) but never materializes the
    # [B, T, V] soft-label tensor — at V=32k that tensor costs more HBM
    # traffic than a whole decoder layer. The one_hot/label_smooth ops
    # remain available (and tested) for programs that want explicit
    # soft labels, e.g. distillation targets.
    flat_logits = fluid.layers.reshape(logits, shape=[-1, trg_vocab_size])
    flat_label = fluid.layers.reshape(label, shape=[-1, 1])
    cost = fluid.layers.softmax_with_cross_entropy(flat_logits, flat_label)
    if label_smooth_eps:
        neg_sum_logp = fluid.layers.scale(
            fluid.layers.reduce_sum(
                fluid.layers.log_softmax(flat_logits), dim=-1, keep_dim=True
            ),
            scale=-1.0,
        )
        cost = fluid.layers.elementwise_add(
            fluid.layers.scale(cost, scale=1.0 - label_smooth_eps),
            fluid.layers.scale(
                neg_sum_logp, scale=label_smooth_eps / trg_vocab_size
            ),
        )

    # Mask loss on padded target positions.
    trg_len = fluid.layers.data("trg_len", shape=[1], dtype="int64")
    trg_mask = fluid.layers.sequence_mask(
        trg_len, maxlen=max_length, dtype="float32"
    )
    cost = fluid.layers.reshape(cost, shape=[-1, max_length])
    masked = fluid.layers.elementwise_mul(cost, trg_mask)
    total = fluid.layers.reduce_sum(masked)
    denom = fluid.layers.reduce_sum(trg_mask)
    avg_cost = fluid.layers.elementwise_div(total, denom)

    feeds = [src, src_len, trg, trg_len, label]
    return avg_cost, feeds, {"logits": logits}
