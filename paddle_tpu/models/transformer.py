"""Transformer encoder-decoder for machine translation.

Reference parity: the reference's Transformer benchmark model
(``tests/unittests/dist_transformer.py`` / ``benchmark/fluid/models/
machine_translation.py`` attention seq2seq). TPU-first differences:
attention is the fused scaled_dot_product_attention op (Pallas flash on
TPU), sequences are dense-padded [batch, T] with explicit length masks,
and pre-norm residual blocks (better large-scale training stability).
"""

import paddle_tpu as fluid


def _ffn(x, d_model, d_inner, name):
    h = fluid.layers.fc(
        input=x, size=d_inner, num_flatten_dims=2, act="relu",
        name=name + "_fc1",
    )
    return fluid.layers.fc(
        input=h, size=d_model, num_flatten_dims=2, name=name + "_fc2"
    )


def _prenorm(x, name):
    return fluid.layers.layer_norm(
        x, begin_norm_axis=2, name=name + "_ln"
    )


def _residual(x, y, dropout, is_test, name):
    if dropout:
        y = fluid.layers.dropout(y, dropout_prob=dropout, is_test=is_test)
    return fluid.layers.elementwise_add(x, y)


def _self_attention_block(x, mask, n_head, d_model, dropout, is_test, name):
    """Pre-norm self-attention + residual — the shared first half of an
    encoder layer (dense-FFN here, MoE-FFN in switch_transformer)."""
    attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_attn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=mask,
        is_test=is_test,
        name=name + "_mha",
    )
    return _residual(x, attn, dropout, is_test, name + "_res1")


def encoder_layer(x, mask, n_head, d_model, d_inner, dropout, is_test, name):
    x = _self_attention_block(x, mask, n_head, d_model, dropout, is_test,
                              name)
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res2")


def decoder_layer(x, enc_out, cross_mask, n_head, d_model,
                  d_inner, dropout, is_test, name):
    self_attn = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_sattn"), None, None,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        causal=True,
        is_test=is_test,
        name=name + "_smha",
    )
    x = _residual(x, self_attn, dropout, is_test, name + "_res1")
    cross = fluid.layers.multi_head_attention(
        _prenorm(x, name + "_cattn"), enc_out, enc_out,
        d_key=d_model // n_head,
        d_value=d_model // n_head,
        d_model=d_model,
        n_head=n_head,
        mask=cross_mask,
        is_test=is_test,
        name=name + "_cmha",
    )
    x = _residual(x, cross, dropout, is_test, name + "_res2")
    ff = _ffn(_prenorm(x, name + "_ffn"), d_model, d_inner, name + "_ffn")
    return _residual(x, ff, dropout, is_test, name + "_res3")


def build(
    src_vocab_size=1000,
    trg_vocab_size=1000,
    max_length=64,
    n_layer=2,
    n_head=4,
    d_model=128,
    d_inner=512,
    dropout=0.1,
    label_smooth_eps=0.1,
    is_test=False,
):
    """Returns (avg_cost, feeds, extras). Feeds: src_word [B,S], src_len
    [B,1], trg_word [B,T] (decoder input), trg_len [B,1], label [B,T]."""
    src = fluid.layers.data("src_word", shape=[max_length], dtype="int64")
    src_len = fluid.layers.data("src_len", shape=[1], dtype="int64")
    trg = fluid.layers.data("trg_word", shape=[max_length], dtype="int64")
    label = fluid.layers.data("label", shape=[max_length], dtype="int64")

    src_mask = fluid.layers.sequence_mask(
        src_len, maxlen=max_length, dtype="float32"
    )  # [B, S] validity

    # Embeddings + sinusoid position encoding
    src_emb = fluid.layers.embedding(
        input=src, size=[src_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="src_emb"),
    )
    src_emb = fluid.layers.scale(src_emb, scale=d_model ** 0.5)
    enc_in = fluid.layers.add_position_encoding(src_emb)

    trg_emb = fluid.layers.embedding(
        input=trg, size=[trg_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="trg_emb"),
    )
    trg_emb = fluid.layers.scale(trg_emb, scale=d_model ** 0.5)
    dec_in = fluid.layers.add_position_encoding(trg_emb)

    enc = enc_in
    for i in range(n_layer):
        enc = encoder_layer(
            enc, src_mask, n_head, d_model, d_inner, dropout, is_test,
            "enc_%d" % i,
        )
    enc = _prenorm(enc, "enc_final")

    dec = dec_in
    for i in range(n_layer):
        dec = decoder_layer(
            dec, enc, src_mask, n_head, d_model, d_inner, dropout,
            is_test, "dec_%d" % i,
        )
    dec = _prenorm(dec, "dec_final")

    logits = fluid.layers.fc(
        input=dec, size=trg_vocab_size, num_flatten_dims=2,
        name="proj_logits",
    )

    # Smoothed cross entropy in factored form: with q = eps/V + (1-eps)*onehot,
    #   -sum_i q_i * logp_i = (1-eps) * hardCE + (eps/V) * (-sum_i logp_i),
    # algebraically identical to one_hot -> label_smooth -> soft-label CE
    # (the reference benchmark's formulation) but never materializes the
    # [B, T, V] soft-label tensor — at V=32k that tensor costs more HBM
    # traffic than a whole decoder layer. The one_hot/label_smooth ops
    # remain available (and tested) for programs that want explicit
    # soft labels, e.g. distillation targets.
    flat_logits = fluid.layers.reshape(logits, shape=[-1, trg_vocab_size])
    flat_label = fluid.layers.reshape(label, shape=[-1, 1])
    cost = fluid.layers.softmax_with_cross_entropy(flat_logits, flat_label)
    if label_smooth_eps:
        neg_sum_logp = fluid.layers.scale(
            fluid.layers.reduce_sum(
                fluid.layers.log_softmax(flat_logits), dim=-1, keep_dim=True
            ),
            scale=-1.0,
        )
        cost = fluid.layers.elementwise_add(
            fluid.layers.scale(cost, scale=1.0 - label_smooth_eps),
            fluid.layers.scale(
                neg_sum_logp, scale=label_smooth_eps / trg_vocab_size
            ),
        )

    # Mask loss on padded target positions.
    trg_len = fluid.layers.data("trg_len", shape=[1], dtype="int64")
    trg_mask = fluid.layers.sequence_mask(
        trg_len, maxlen=max_length, dtype="float32"
    )
    cost = fluid.layers.reshape(cost, shape=[-1, max_length])
    masked = fluid.layers.elementwise_mul(cost, trg_mask)
    total = fluid.layers.reduce_sum(masked)
    denom = fluid.layers.reduce_sum(trg_mask)
    avg_cost = fluid.layers.elementwise_div(total, denom)

    feeds = [src, src_len, trg, trg_len, label]
    return avg_cost, feeds, {"logits": logits}


def build_inference(train_prog, logits):
    """Derive the generation graph from the TRAINED program: clone with
    is_test flipped (inference dropout) and prune to the logits fetch —
    the loss head, backward and optimizer ops all fall away, so running
    it cannot touch the weights. Parameters bind through the shared
    scope. Used by greedy_generate/beam_generate below."""
    from paddle_tpu import io

    return io.prune_program(
        train_prog.clone(for_test=True),
        ["src_word", "src_len", "trg_word"],
        [logits.name if hasattr(logits, "name") else logits],
    )


def greedy_generate(exe, infer_prog, logits_var, src, src_len,
                    max_length, bos_id=1, eos_id=2):
    """Greedy decode by re-running the full (fixed-shape) decoder over
    the growing prefix — the whole-program-XLA analog of the reference's
    re-score loop; one executable serves every step because shapes never
    change. Returns [B, max_length] int64 (eos-padded)."""
    import numpy as np

    bs = src.shape[0]
    trg = np.full((bs, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    done = np.zeros(bs, bool)
    for t in range(max_length - 1):
        (lg,) = exe.run(
            infer_prog,
            feed={
                "src_word": src,
                "src_len": src_len,
                "trg_word": trg,
            },
            fetch_list=[logits_var],
        )
        nxt = np.asarray(lg)[:, t, :].argmax(-1)
        nxt = np.where(done, eos_id, nxt)
        trg[:, t + 1] = nxt
        done |= nxt == eos_id
        if done.all():
            break
    return trg


def beam_generate(exe, infer_prog, logits_var, src, src_len, max_length,
                  beam_size=4, bos_id=1, eos_id=2, len_penalty=0.6):
    """Beam-search decode over the same fixed-shape program: beams ride
    the batch dimension (B*K rows); the per-step selection (incl.
    finished-beam freezing and first-step duplicate suppression) is
    ops/beam_search_ops.beam_step — the same lattice step the in-graph
    beam_search op uses. A GNMT-style length penalty picks the final
    beam. Returns [B, max_length] int64 (best beam per source)."""
    import numpy as np

    from paddle_tpu.ops.beam_search_ops import beam_step

    bs = src.shape[0]
    K = int(beam_size)
    src_k = np.repeat(src, K, axis=0)
    len_k = np.repeat(src_len, K, axis=0)
    trg = np.full((bs * K, max_length), eos_id, np.int64)
    trg[:, 0] = bos_id
    # int32: beam_step mirrors the dtype, and jnp int64 would
    # warn-and-truncate with x64 disabled
    pre_ids = np.full((bs, K), bos_id, np.int32)
    pre_scores = np.full((bs, K), -1e9, np.float32)
    pre_scores[:, 0] = 0.0  # only beam 0 live at t=0 (no K duplicates)
    rows = np.arange(bs)[:, None]
    for t in range(max_length - 1):
        (lg,) = exe.run(
            infer_prog,
            feed={
                "src_word": src_k,
                "src_len": len_k,
                "trg_word": trg,
            },
            fetch_list=[logits_var],
        )
        step = np.asarray(lg)[:, t, :].astype(np.float64)  # [B*K, V]
        mx = step.max(-1, keepdims=True)
        step = step - mx - np.log(
            np.exp(step - mx).sum(-1, keepdims=True))  # stable log softmax
        token, sel_scores, parent = beam_step(
            pre_ids, pre_scores, step.reshape(
                bs, K, -1).astype(np.float32), eos_id)
        token = np.asarray(token)
        parent = np.asarray(parent)
        # prefixes follow their beams (the decoder re-reads them)
        trg_bk = trg.reshape(bs, K, max_length)[rows, parent]
        trg_bk[:, :, t + 1] = token
        trg = trg_bk.reshape(bs * K, max_length)
        pre_ids = token
        pre_scores = np.asarray(sel_scores)
        if (token == eos_id).all():
            break
    # length penalty over the eos-trimmed lengths
    trg_bk = trg.reshape(bs, K, max_length)
    tail = trg_bk[:, :, 1:]
    has_eos = (tail == eos_id).any(-1)
    first = (tail == eos_id).argmax(-1)
    lengths = np.where(has_eos, first + 1, max_length).astype(np.float64)
    lp = ((5.0 + lengths) / 6.0) ** len_penalty
    best = (pre_scores.astype(np.float64) / lp).argmax(-1)
    return trg_bk[np.arange(bs), best]
