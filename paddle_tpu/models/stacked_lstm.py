"""Stacked dynamic-LSTM sentiment model.

Reference parity: ``benchmark/fluid/models/stacked_dynamic_lstm.py`` (IMDB
sentiment: embedding -> fc -> stacked LSTM layers -> pooled -> softmax).
Dense-padded regime: input is [batch, seq_len] token ids + [batch] lengths
instead of an LoD tensor.
"""

import paddle_tpu as fluid


def build(
    seq_len=80,
    dict_size=5000,
    emb_dim=64,
    hid_dim=64,
    stacked_num=3,
    class_num=2,
):
    data = fluid.layers.data(name="words", shape=[seq_len], dtype="int64")
    length = fluid.layers.data(name="length", shape=[1], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")

    emb = fluid.layers.embedding(
        input=data, size=[dict_size, emb_dim], is_sparse=False
    )

    fc1 = fluid.layers.fc(input=emb, size=hid_dim * 4, num_flatten_dims=2)
    lstm1, _ = fluid.layers.dynamic_lstm(
        input=fc1, size=hid_dim * 4, length=length
    )

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(
            input=inputs, size=hid_dim * 4, num_flatten_dims=2
        )
        lstm, _ = fluid.layers.dynamic_lstm(
            input=fc, size=hid_dim * 4, length=length, is_reverse=False
        )
        inputs = [fc, lstm]

    fc_last = fluid.layers.sequence_pool(
        input=inputs[0], pool_type="max", length=length
    )
    lstm_last = fluid.layers.sequence_pool(
        input=inputs[1], pool_type="max", length=length
    )

    prediction = fluid.layers.fc(
        input=[fc_last, lstm_last], size=class_num, act="softmax"
    )
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return avg_cost, [data, length, label], {
        "accuracy": acc,
        "predict": prediction,
    }
