"""Benchmark model zoo (benchmark/fluid/models/ parity): each model module
exposes ``build(...) -> (loss, feeds, extras)`` constructing the Fluid-style
program for the Executor to compile whole-graph to XLA."""

from paddle_tpu.models import mnist  # noqa: F401
from paddle_tpu.models import vgg  # noqa: F401
from paddle_tpu.models import resnet  # noqa: F401
from paddle_tpu.models import stacked_lstm  # noqa: F401
from paddle_tpu.models import transformer  # noqa: F401
from paddle_tpu.models import switch_transformer  # noqa: F401
from paddle_tpu.models import machine_translation  # noqa: F401
from paddle_tpu.models import se_resnext  # noqa: F401
from paddle_tpu.models import googlenet  # noqa: F401
from paddle_tpu.models import alexnet  # noqa: F401
from paddle_tpu.models import ssd  # noqa: F401
