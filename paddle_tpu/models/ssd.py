"""SSD-style single-shot detector on a small VGG-ish backbone.

Reference parity: the SSD recipe the reference's detection layers exist to
serve (python/paddle/fluid/layers/detection.py multi_box_head + ssd_loss;
their models repo's mobilenet-ssd config, scaled down). TPU-first: dense
padded ground truth [N, G, 4]/[N, G] (docs/LOD_DESIGN.md), static-shape
NMS for the eval head.
"""

import paddle_tpu as fluid


def _backbone(img):
    """Three stride-2 stages; the last two feed the multibox head."""
    c1 = fluid.layers.conv2d(img, 32, 3, stride=2, padding=1, act="relu")
    c1 = fluid.layers.conv2d(c1, 32, 3, padding=1, act="relu")
    c2 = fluid.layers.conv2d(c1, 64, 3, stride=2, padding=1, act="relu")
    c2 = fluid.layers.conv2d(c2, 64, 3, padding=1, act="relu")
    c3 = fluid.layers.conv2d(c2, 128, 3, stride=2, padding=1, act="relu")
    return c2, c3


def build(img_shape=(3, 96, 96), class_num=4, max_gt=8,
          nms_keep_top_k=50, score_threshold=0.01):
    """Returns (loss, feeds, extras). Feeds: image [N,C,H,W], gt_box
    [N, max_gt, 4] zero-padded, gt_label [N, max_gt] int32. Extras carry
    the eval head: nmsed_out [N, keep_top_k, 6] and map_eval (detection
    mAP for the batch)."""
    img = fluid.layers.data("image", list(img_shape))
    gt_box = fluid.layers.data("gt_box", [max_gt, 4])
    gt_label = fluid.layers.data("gt_label", [max_gt], dtype="int32")

    f2, f3 = _backbone(img)
    size = img_shape[-1]
    locs, confs, boxes, variances = fluid.layers.multi_box_head(
        inputs=[f2, f3],
        image=img,
        base_size=size,
        num_classes=class_num,
        aspect_ratios=[[1.0, 2.0], [1.0, 2.0]],
        min_sizes=[size * 0.2, size * 0.5],
        max_sizes=[size * 0.5, size * 0.8],
        flip=True,
        clip=True,
    )

    loss = fluid.layers.ssd_loss(locs, confs, gt_box, gt_label,
                                 boxes, variances)
    loss = fluid.layers.mean(loss)

    nmsed_out = fluid.layers.detection_output(
        locs, confs, boxes, variances,
        score_threshold=score_threshold, keep_top_k=nms_keep_top_k)
    map_eval = fluid.layers.detection_map(
        nmsed_out, gt_label, gt_box, class_num=class_num)

    return loss, [img, gt_box, gt_label], {
        "nmsed_out": nmsed_out,
        "map_eval": map_eval,
        "mbox_locs": locs,
        "mbox_confs": confs,
    }
