"""GoogLeNet / Inception-v1 (benchmark/paddle/image/googlenet.py capability,
one of the BASELINE.md benchmark families): inception concat blocks + two
auxiliary classifier towers contributing 0.3-weighted losses during
training."""

import paddle_tpu as fluid


def conv_layer(input, num_filters, filter_size, stride=1, padding=None,
               act="relu"):
    if padding is None:
        padding = (filter_size - 1) // 2
    return fluid.layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act=act,
    )


def inception(input, c1, c3r, c3, c5r, c5, proj):
    b1 = conv_layer(input, c1, 1)
    b3 = conv_layer(conv_layer(input, c3r, 1), c3, 3)
    b5 = conv_layer(conv_layer(input, c5r, 1), c5, 5)
    pool = fluid.layers.pool2d(
        input=input, pool_size=3, pool_stride=1, pool_padding=1,
        pool_type="max",
    )
    bp = conv_layer(pool, proj, 1)
    return fluid.layers.concat([b1, b3, b5, bp], axis=1)


def _aux_head(input, class_dim, is_train):
    # 5x5/stride-3 matches the 224px reference geometry (14x14 -> 4x4);
    # smaller feature maps would pool to zero size, so fall back to global.
    spatial = min(int(input.shape[2]), int(input.shape[3]))
    if spatial >= 5:
        pool = fluid.layers.pool2d(
            input=input, pool_size=5, pool_stride=3, pool_type="avg"
        )
    else:
        pool = fluid.layers.pool2d(
            input=input, pool_type="avg", global_pooling=True
        )
    conv = conv_layer(pool, 128, 1)
    fc1 = fluid.layers.fc(input=conv, size=1024, act="relu")
    drop = fluid.layers.dropout(fc1, dropout_prob=0.7, is_test=not is_train)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")


def googlenet(input, class_dim, is_train=True):
    conv1 = conv_layer(input, 64, 7, stride=2)
    pool1 = fluid.layers.pool2d(
        input=conv1, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )
    conv2 = conv_layer(conv_layer(pool1, 64, 1), 192, 3)
    pool2 = fluid.layers.pool2d(
        input=conv2, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )

    i3a = inception(pool2, 64, 96, 128, 16, 32, 32)
    i3b = inception(i3a, 128, 128, 192, 32, 96, 64)
    pool3 = fluid.layers.pool2d(
        input=i3b, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )

    i4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    i4b = inception(i4a, 160, 112, 224, 24, 64, 64)
    i4c = inception(i4b, 128, 128, 256, 24, 64, 64)
    i4d = inception(i4c, 112, 144, 288, 32, 64, 64)
    i4e = inception(i4d, 256, 160, 320, 32, 128, 128)
    pool4 = fluid.layers.pool2d(
        input=i4e, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )

    i5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    i5b = inception(i5a, 384, 192, 384, 48, 128, 128)
    pool5 = fluid.layers.pool2d(
        input=i5b, pool_type="avg", global_pooling=True
    )
    drop = fluid.layers.dropout(pool5, dropout_prob=0.4,
                                is_test=not is_train)
    main_out = fluid.layers.fc(input=drop, size=class_dim, act="softmax")

    aux1 = _aux_head(i4a, class_dim, is_train)
    aux2 = _aux_head(i4d, class_dim, is_train)
    return main_out, aux1, aux2


def build(img_shape=(3, 224, 224), class_num=1000, dtype="float32",
          is_train=True, use_aux_heads=True):
    images = fluid.layers.data(name="pixel", shape=list(img_shape),
                               dtype=dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    out, aux1, aux2 = googlenet(images, class_num, is_train=is_train)
    cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=out, label=label)
    )
    if use_aux_heads and is_train:
        cost1 = fluid.layers.mean(
            fluid.layers.cross_entropy(input=aux1, label=label)
        )
        cost2 = fluid.layers.mean(
            fluid.layers.cross_entropy(input=aux2, label=label)
        )
        cost = fluid.layers.elementwise_add(
            cost,
            fluid.layers.scale(
                fluid.layers.elementwise_add(cost1, cost2), scale=0.3
            ),
        )
    acc = fluid.layers.accuracy(input=out, label=label)
    return cost, [images, label], {"accuracy": acc, "predict": out}
