"""SE-ResNeXt (benchmark/fluid/models/se_resnext.py parity): grouped-conv
bottlenecks (cardinality 32/64) with squeeze-and-excitation channel gating.
The grouped 3x3 conv lowers to XLA's feature_group_count path and the SE
gate is two tiny MXU matmuls + a broadcast multiply XLA fuses into the
residual add."""

import paddle_tpu as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_train=True):
    conv = fluid.layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        act=None,
        bias_attr=False,
    )
    return fluid.layers.batch_norm(input=conv, act=act, is_test=not is_train)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = fluid.layers.pool2d(
        input=input, pool_type="avg", global_pooling=True
    )
    squeeze = fluid.layers.fc(
        input=pool, size=num_channels // reduction_ratio, act="relu"
    )
    excitation = fluid.layers.fc(
        input=squeeze, size=num_channels, act="sigmoid"
    )
    return fluid.layers.elementwise_mul(x=input, y=excitation, axis=0)


def shortcut(input, ch_out, stride, is_train=True):
    ch_in = int(input.shape[1])
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(
            input, ch_out, 1, stride, is_train=is_train
        )
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio, is_train=True):
    conv0 = conv_bn_layer(
        input, num_filters, 1, act="relu", is_train=is_train
    )
    conv1 = conv_bn_layer(
        conv0, num_filters, 3, stride, groups=cardinality, act="relu",
        is_train=is_train,
    )
    conv2 = conv_bn_layer(
        conv1, num_filters * 2, 1, act=None, is_train=is_train
    )
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride, is_train=is_train)
    return fluid.layers.elementwise_add(short, scale, act="relu")


def se_resnext_imagenet(input, class_dim, depth=50, is_train=True):
    cfg = {
        50: ([3, 4, 6, 3], 32, 16, [128, 256, 512, 1024]),
        101: ([3, 4, 23, 3], 32, 16, [128, 256, 512, 1024]),
        152: ([3, 8, 36, 3], 64, 16, [128, 256, 512, 1024]),
    }
    stages, cardinality, reduction_ratio, num_filters = cfg[depth]
    if depth == 152:
        conv = conv_bn_layer(input, 64, 3, 2, act="relu", is_train=is_train)
        conv = conv_bn_layer(conv, 64, 3, act="relu", is_train=is_train)
        conv = conv_bn_layer(conv, 128, 3, act="relu", is_train=is_train)
    else:
        conv = conv_bn_layer(input, 64, 7, 2, act="relu", is_train=is_train)
    conv = fluid.layers.pool2d(
        input=conv, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )
    for block, n in enumerate(stages):
        for i in range(n):
            conv = bottleneck_block(
                conv,
                num_filters[block],
                2 if i == 0 and block != 0 else 1,
                cardinality,
                reduction_ratio,
                is_train=is_train,
            )
    pool = fluid.layers.pool2d(
        input=conv, pool_type="avg", global_pooling=True
    )
    drop = fluid.layers.dropout(pool, dropout_prob=0.2, is_test=not is_train)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")


def build(img_shape=(3, 224, 224), class_num=1000, depth=50, dtype="float32",
          is_train=True):
    images = fluid.layers.data(name="pixel", shape=list(img_shape),
                               dtype=dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = se_resnext_imagenet(images, class_num, depth=depth,
                                  is_train=is_train)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    return avg_cost, [images, label], {"accuracy": acc, "predict": predict}
