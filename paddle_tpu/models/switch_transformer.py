"""Switch-Transformer encoder classifier: sparse MoE FFN layers.

Scope beyond the reference (which predates MoE); built from the same
blocks as models/transformer.py with every ``moe_every``-th encoder
layer's dense FFN replaced by ``fluid.layers.moe_ffn`` (Switch routing,
ops/moe_ops.py). The load-balancing auxiliary losses are summed and
folded into the returned training loss with weight ``aux_weight``.

Expert parallelism: shard the ``*_moe_w1/w2/b1/b2`` parameters on their
expert dim over a mesh axis (see tests/test_moe.py and
__graft_entry__._dryrun_expert_parallel for the override recipe).
"""

import paddle_tpu as fluid

from paddle_tpu.models.transformer import (
    _prenorm,
    _residual,
    _self_attention_block,
    encoder_layer,
)


def _moe_encoder_layer(x, mask, n_head, d_model, d_inner, num_experts,
                       top_k, dropout, is_test, name):
    x = _self_attention_block(x, mask, n_head, d_model, dropout, is_test,
                              name)
    ff, aux = fluid.layers.moe_ffn(
        _prenorm(x, name + "_ffn"), num_experts=num_experts,
        d_hidden=d_inner, top_k=top_k, mask=mask,
        param_attr=fluid.ParamAttr(name=name + "_moe"),
        name=name + "_moe",
    )
    return _residual(x, ff, dropout, is_test, name + "_res2"), aux


def build(
    vocab_size=1000,
    max_length=64,
    n_layer=4,
    n_head=4,
    d_model=128,
    d_inner=256,
    num_experts=4,
    top_k=1,
    moe_every=2,
    aux_weight=1e-2,
    num_classes=2,
    dropout=0.0,
    is_test=False,
):
    """Sequence classifier over a Switch encoder stack. Returns
    (loss, feeds, extras): extras carries ``logits`` and the summed
    ``aux_loss``. Feeds: word [B, T], seq_len [B, 1], label [B, 1]."""
    word = fluid.layers.data("word", shape=[max_length], dtype="int64")
    seq_len = fluid.layers.data("seq_len", shape=[1], dtype="int64")
    label = fluid.layers.data("label", shape=[1], dtype="int64")

    mask = fluid.layers.sequence_mask(
        seq_len, maxlen=max_length, dtype="float32")
    emb = fluid.layers.embedding(
        input=word, size=[vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="switch_emb"))
    emb = fluid.layers.scale(emb, scale=d_model ** 0.5)
    h = fluid.layers.add_position_encoding(emb)

    aux_losses = []
    for i in range(n_layer):
        name = "switch_%d" % i
        if moe_every and (i + 1) % moe_every == 0:
            h, aux = _moe_encoder_layer(
                h, mask, n_head, d_model, d_inner, num_experts, top_k,
                dropout, is_test, name)
            aux_losses.append(aux)
        else:
            h = encoder_layer(
                h, mask, n_head, d_model, d_inner, dropout, is_test, name)
    h = _prenorm(h, "switch_final")

    # masked mean-pool over valid positions, then classify
    m = fluid.layers.unsqueeze(mask, axes=[2])
    pooled = fluid.layers.elementwise_div(
        fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(h, m), dim=1),
        fluid.layers.reduce_sum(m, dim=1),
    )
    logits = fluid.layers.fc(pooled, size=num_classes, name="switch_head")
    ce = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))

    aux_total = None
    for a in aux_losses:
        am = fluid.layers.mean(a)
        aux_total = am if aux_total is None else aux_total + am
    loss = ce if aux_total is None else ce + aux_weight * aux_total

    feeds = [word, seq_len, label]
    return loss, feeds, {"logits": logits, "aux_loss": aux_total,
                         "ce_loss": ce}
