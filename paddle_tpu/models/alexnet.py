"""AlexNet (benchmark/paddle/image/alexnet.py capability, a BASELINE.md
benchmark family): 5 convs with LRN + 3 FCs with dropout."""

import paddle_tpu as fluid


def alexnet(input, class_dim, is_train=True, use_lrn=True):
    conv1 = fluid.layers.conv2d(
        input=input, num_filters=96, filter_size=11, stride=4, padding=2,
        act="relu",
    )
    if use_lrn:
        conv1 = fluid.layers.lrn(conv1, n=5, alpha=1e-4, beta=0.75)
    pool1 = fluid.layers.pool2d(
        input=conv1, pool_size=3, pool_stride=2, pool_type="max"
    )

    conv2 = fluid.layers.conv2d(
        input=pool1, num_filters=256, filter_size=5, padding=2, groups=2,
        act="relu",
    )
    if use_lrn:
        conv2 = fluid.layers.lrn(conv2, n=5, alpha=1e-4, beta=0.75)
    pool2 = fluid.layers.pool2d(
        input=conv2, pool_size=3, pool_stride=2, pool_type="max"
    )

    conv3 = fluid.layers.conv2d(
        input=pool2, num_filters=384, filter_size=3, padding=1, act="relu"
    )
    conv4 = fluid.layers.conv2d(
        input=conv3, num_filters=384, filter_size=3, padding=1, groups=2,
        act="relu",
    )
    conv5 = fluid.layers.conv2d(
        input=conv4, num_filters=256, filter_size=3, padding=1, groups=2,
        act="relu",
    )
    pool5 = fluid.layers.pool2d(
        input=conv5, pool_size=3, pool_stride=2, pool_type="max"
    )

    fc6 = fluid.layers.fc(input=pool5, size=4096, act="relu")
    drop6 = fluid.layers.dropout(fc6, dropout_prob=0.5, is_test=not is_train)
    fc7 = fluid.layers.fc(input=drop6, size=4096, act="relu")
    drop7 = fluid.layers.dropout(fc7, dropout_prob=0.5, is_test=not is_train)
    return fluid.layers.fc(input=drop7, size=class_dim, act="softmax")


def build(img_shape=(3, 224, 224), class_num=1000, dtype="float32",
          is_train=True, use_lrn=True):
    images = fluid.layers.data(name="pixel", shape=list(img_shape),
                               dtype=dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = alexnet(images, class_num, is_train=is_train, use_lrn=use_lrn)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    return avg_cost, [images, label], {"accuracy": acc, "predict": predict}
