"""Optimizer family: minimize = append_backward + accumulators + update OPS.

Reference parity: python/paddle/fluid/optimizer.py:41 (Optimizer base),
:274-1313 (SGD/Momentum/LARS/Adagrad/Adam/Adamax/DecayedAdagrad/Adadelta/
RMSProp/Ftrl/ModelAverage). Update rules live in optimizer ops
(paddle_tpu/ops/optimizer_ops.py) so the whole train step — forward,
backward, clip/regularize, update — compiles to ONE XLA program.
"""

from collections import defaultdict

from paddle_tpu import framework, initializer, unique_name
from paddle_tpu.backward import append_backward
from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, int, Variable)):
            raise TypeError("learning_rate must be float or Variable")
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        program = framework.default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        self._learning_rate_map[program] = self.helper.create_global_variable(
            name=unique_name.generate("learning_rate"),
            shape=[1],
            dtype="float32",
            persistable=True,
            initializer=initializer.ConstantInitializer(
                float(self._learning_rate)
            ),
        )

    def _global_learning_rate(self, program=None):
        program = program or framework.default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        from paddle_tpu.layers import nn

        return nn.scale(base, scale=float(param_lr))

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        var = self.helper.create_global_variable(
            name=unique_name.generate("%s_%s" % (param.name, name)),
            shape=shape or list(param.shape),
            dtype=dtype or param.dtype,
            persistable=True,
            initializer=initializer.ConstantInitializer(float(fill_value)),
        )
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks for subclasses ----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- driver -------------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        block = program.global_block()
        self.helper = LayerHelper(
            self.__class__.__name__, startup_program=startup_program
        )
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None]
        )
        self._create_global_learning_rate()

        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            with program._optimized_guard(list(param_and_grad)):
                if param_and_grad[0].trainable:
                    optimize_ops.append(
                        self._append_optimize_op(block, param_and_grad)
                    )
        with program._optimized_guard([]):
            self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from paddle_tpu import clip as clip_mod
        from paddle_tpu import regularizer as reg_mod

        # All graph surgery happens on the loss's own program (reference
        # guards with loss.block.program, optimizer.py minimize).
        sp_guard = framework.program_guard(
            loss.block.program,
            startup_program or framework.default_startup_program(),
        )
        with sp_guard:
            params_grads = append_backward(loss, parameter_list, no_grad_set)
            params_grads = sorted(params_grads, key=lambda x: x[0].name)
            params_grads = clip_mod.append_gradient_clip_ops(params_grads)
            params_grads = reg_mod.append_regularization_ops(
                params_grads, self.regularization
            )
            optimize_ops = self._create_optimization_pass(
                params_grads, loss, startup_program
            )
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super(SGDOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [param_and_grad[0].name],
                "Grad": [param_and_grad[1].name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [param_and_grad[0].name]},
        )


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super(MomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(
            self._velocity_acc_str, param_and_grad[0]
        )
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [param_and_grad[0].name],
                "Grad": [param_and_grad[1].name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [param_and_grad[0].name],
                "VelocityOut": [velocity.name],
            },
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kwargs):
        super(LarsMomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(
            self._velocity_acc_str, param_and_grad[0]
        )
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [param_and_grad[0].name],
                "Grad": [param_and_grad[1].name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [param_and_grad[0].name],
                "VelocityOut": [velocity.name],
            },
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [param_and_grad[0].name],
                "Grad": [param_and_grad[1].name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [param_and_grad[0].name],
                "MomentOut": [moment.name],
            },
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super(AdamOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(
                self._beta1_pow_acc_str, p, fill_value=self._beta1, shape=[1]
            )
            self._add_accumulator(
                self._beta2_pow_acc_str, p, fill_value=self._beta2, shape=[1]
            )

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment1 = self._get_accumulator(self._moment1_acc_str, p)
        moment2 = self._get_accumulator(self._moment2_acc_str, p)
        beta1_pow = self._get_accumulator(self._beta1_pow_acc_str, p)
        beta2_pow = self._get_accumulator(self._beta2_pow_acc_str, p)
        return block.append_op(
            type="adam",
            inputs={
                "Param": [p.name],
                "Grad": [param_and_grad[1].name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
                "Moment1": [moment1.name],
                "Moment2": [moment2.name],
                "Beta1Pow": [beta1_pow.name],
                "Beta2Pow": [beta2_pow.name],
            },
            outputs={
                "ParamOut": [p.name],
                "Moment1Out": [moment1.name],
                "Moment2Out": [moment2.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block, parameters_and_grads):
        """Scale beta-pow accumulators (optimizer.py Adam._finish_update)."""
        for p, g in parameters_and_grads:
            if g is None:
                continue
            for acc_str, beta in [
                (self._beta1_pow_acc_str, self._beta1),
                (self._beta2_pow_acc_str, self._beta2),
            ]:
                acc = self._get_accumulator(acc_str, p)
                block.append_op(
                    type="scale",
                    inputs={"X": [acc.name]},
                    outputs={"Out": [acc.name]},
                    attrs={"scale": beta},
                )


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamaxOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(
                self._beta1_pow_acc_str, p, fill_value=self._beta1, shape=[1]
            )

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment = self._get_accumulator(self._moment_acc_str, p)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, p)
        beta1_pow = self._get_accumulator(self._beta1_pow_acc_str, p)
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [p.name],
                "Grad": [param_and_grad[1].name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
                "Moment": [moment.name],
                "InfNorm": [inf_norm.name],
                "Beta1Pow": [beta1_pow.name],
            },
            outputs={
                "ParamOut": [p.name],
                "MomentOut": [moment.name],
                "InfNormOut": [inf_norm.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block, parameters_and_grads):
        for p, g in parameters_and_grads:
            if g is None:
                continue
            acc = self._get_accumulator(self._beta1_pow_acc_str, p)
            block.append_op(
                type="scale",
                inputs={"X": [acc.name]},
                outputs={"Out": [acc.name]},
                attrs={"scale": self._beta1},
            )


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [param_and_grad[0].name],
                "Grad": [param_and_grad[1].name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [param_and_grad[0].name],
                "MomentOut": [moment.name],
            },
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, p)
        asu = self._get_accumulator(self._avg_squared_update_acc_str, p)
        return block.append_op(
            type="adadelta",
            inputs={
                "Param": [p.name],
                "Grad": [param_and_grad[1].name],
                "AvgSquaredGrad": [asg.name],
                "AvgSquaredUpdate": [asu.name],
            },
            outputs={
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [asg.name],
                "AvgSquaredUpdateOut": [asu.name],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"
    _mean_grad_acc_str = "mean_grad"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)
            self._add_accumulator(self._mean_grad_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        momentum = self._get_accumulator(self._momentum_acc_str, p)
        mean_square = self._get_accumulator(self._mean_square_acc_str, p)
        mean_grad = self._get_accumulator(self._mean_grad_acc_str, p)
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [p.name],
                "Grad": [param_and_grad[1].name],
                "Moment": [momentum.name],
                "MeanSquare": [mean_square.name],
                "MeanGrad": [mean_grad.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [p.name],
                "MomentOut": [momentum.name],
                "MeanSquareOut": [mean_square.name],
                "MeanGradOut": [mean_grad.name],
            },
            attrs={
                "epsilon": self._epsilon,
                "decay": self._rho,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        squared = self._get_accumulator(self._squared_acc_str, p)
        linear = self._get_accumulator(self._linear_acc_str, p)
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p.name],
                "Grad": [param_and_grad[1].name],
                "SquaredAccumulator": [squared.name],
                "LinearAccumulator": [linear.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [p.name],
                "SquaredAccumOut": [squared.name],
                "LinearAccumOut": [linear.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class ModelAverage(Optimizer):
    """Maintains running averages of parameters for eval
    (optimizer.py:1313 parity) — apply()/restore() swap averaged params."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super(ModelAverage, self).__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._sum_vars = {}

    def _append_average_accumulate_op(self, param):
        self.helper = LayerHelper("model_average")
        sum_var = self._add_accumulator("sum", param)
        num_var = self._add_accumulator("num_acc", param, shape=[1],
                                        dtype="float32")
        block = framework.default_main_program().global_block()
        block.append_op(
            type="sum",
            inputs={"X": [sum_var.name, param.name]},
            outputs={"Out": [sum_var.name]},
        )
        block.append_op(
            type="increment",
            inputs={"X": [num_var.name]},
            outputs={"Out": [num_var.name]},
            attrs={"step": 1.0},
        )
        self._sum_vars[param.name] = (sum_var, num_var)

    def build(self, params):
        for p in params:
            self._append_average_accumulate_op(p)

    def apply(self, executor, scope=None):
        """Overwrite params with their running averages (host-side)."""
        import numpy as np

        from paddle_tpu.executor import global_scope

        scope = scope or global_scope()
        self._backup = {}
        for pname, (sum_var, num_var) in self._sum_vars.items():
            p = scope.get_value(pname)
            s = scope.get_value(sum_var.name)
            n = scope.get_value(num_var.name)
            if p is None or s is None or n is None:
                continue
            self._backup[pname] = p
            denom = max(float(np.asarray(n).reshape(-1)[0]), 1.0)
            scope.set_value(pname, (np.asarray(s) / denom).astype(
                np.asarray(p).dtype))

    def restore(self, executor, scope=None):
        from paddle_tpu.executor import global_scope

        scope = scope or global_scope()
        for pname, val in getattr(self, "_backup", {}).items():
            scope.set_value(pname, val)
        self._backup = {}


# Public aliases matching fluid.optimizer.
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
