"""Attention layers: scaled dot-product + multi-head attention.

Reference role: composed-op attention in the reference's Transformer test
model (tests/unittests/dist_transformer.py multi_head_attention); here the
core is the fused scaled_dot_product_attention op (Pallas flash kernel on
TPU, paddle_tpu/kernels/flash_attention.py).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "scaled_dot_product_attention",
    "multi_head_attention",
    "paged_attention",
    "paged_kv_write",
    "paged_kv_prefill",
    "paged_copy_page",
    "grouped_cross_attention",
    "paged_tree_attention",
    "paged_spec_kv_write",
    "paged_spec_kv_compact",
    "slot_decode_sample",
    "slot_beam_search",
    "slot_speculative_accept",
    "label_smooth",
    "add_position_encoding",
    "rotary_position_embedding",
    "moe_ffn",
]


def scaled_dot_product_attention(
    queries, keys, values, mask=None, causal=False, sm_scale=None,
    impl="auto", seq_parallel_axis=None, kv_group=1, window=0, name=None
):
    """Fused attention over [batch, heads, seq, head_dim] tensors.

    With ``seq_parallel_axis`` (the name of a ParallelExecutor mesh
    axis), the op runs ring attention with the sequence sharded over
    that axis — in-program context parallelism for sequences too long
    for one chip."""
    helper = LayerHelper("sdpa", name=name)
    out = helper.create_variable_for_type_inference(queries.dtype)
    inputs = {"Q": [queries], "K": [keys], "V": [values]}
    if mask is not None:
        inputs["Mask"] = [mask]
    helper.append_op(
        type="scaled_dot_product_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "causal": causal,
            "sm_scale": float(sm_scale or 0.0),
            "impl": impl,
            "seq_parallel_axis": seq_parallel_axis or "",
            "kv_group": int(kv_group),
            "window": int(window),
        },
    )
    return out


def multi_head_attention(
    queries,
    keys,
    values,
    d_key,
    d_value,
    d_model,
    n_head=1,
    n_kv_head=None,
    dropout_rate=0.0,
    mask=None,
    causal=False,
    param_attr=None,
    is_test=False,
    name=None,
):
    """Projections + fused attention + output projection.

    queries/keys/values: [batch, seq, d_model]; returns [batch, seq,
    d_model]. All four projections are single fused matmuls (MXU-sized).

    ``n_kv_head`` enables grouped-query attention (GQA; beyond the
    reference): K/V are projected to n_kv_head heads (n_head must be a
    multiple) and the attention op serves each kv head to its query
    group through the kernel's index map — no repeated K/V tensor
    materializes, and the K/V projection weights and any cached K/V
    shrink by n_head/n_kv_head. n_kv_head=1 is multi-query attention.
    """
    from paddle_tpu.layers import nn as nn_layers

    if keys is None:
        keys = queries
    if values is None:
        values = keys

    kv_heads = n_head if n_kv_head is None else int(n_kv_head)
    if kv_heads < 1 or n_head % kv_heads != 0:
        raise ValueError(
            "multi_head_attention: n_kv_head (%d) must be >= 1 and "
            "divide n_head (%d)" % (kv_heads, n_head))
    q = nn_layers.fc(
        input=queries, size=d_key * n_head, num_flatten_dims=2,
        bias_attr=False, param_attr=param_attr,
        name=(name + "_q") if name else None,
    )
    k = nn_layers.fc(
        input=keys, size=d_key * kv_heads, num_flatten_dims=2,
        bias_attr=False, param_attr=param_attr,
        name=(name + "_k") if name else None,
    )
    v = nn_layers.fc(
        input=values, size=d_value * kv_heads, num_flatten_dims=2,
        bias_attr=False, param_attr=param_attr,
        name=(name + "_v") if name else None,
    )

    def split_heads(x, d_head, heads):
        # [B, T, H*dh] -> [B, H, T, dh]
        reshaped = nn_layers.reshape(x, shape=[0, 0, heads, d_head])
        return nn_layers.transpose(reshaped, perm=[0, 2, 1, 3])

    qh = split_heads(q, d_key, n_head)
    kh = split_heads(k, d_key, kv_heads)
    vh = split_heads(v, d_value, kv_heads)

    # grouped K/V ride through the attention op's kv_group attr: the
    # Pallas kernel maps query head h to kv head h // group in its index
    # map, so the repeated K/V never materializes
    ctx = scaled_dot_product_attention(
        qh, kh, vh, mask=mask, causal=causal,
        sm_scale=d_key ** -0.5, kv_group=n_head // kv_heads,
    )
    # [B, H, T, dh] -> [B, T, H*dh]
    merged = nn_layers.reshape(
        nn_layers.transpose(ctx, perm=[0, 2, 1, 3]),
        shape=[0, 0, n_head * d_value],
    )
    if dropout_rate:
        merged = nn_layers.dropout(
            merged, dropout_prob=dropout_rate, is_test=is_test
        )
    return nn_layers.fc(
        input=merged, size=d_model, num_flatten_dims=2, bias_attr=False,
        param_attr=param_attr, name=(name + "_o") if name else None,
    )


def rotary_position_embedding(q, k, position=None, base=10000.0,
                              name=None):
    """RoPE over [batch, heads, seq, head_dim] q/k (rotate-half
    convention); returns (q_rot, k_rot). ``position``: optional [1] int
    offset for KV-cached decoding. Beyond the reference — pairs with
    flash attention and n_kv_head for a modern attention stack."""
    helper = LayerHelper("rope", name=name)
    q_out = helper.create_variable_for_type_inference(q.dtype)
    k_out = helper.create_variable_for_type_inference(k.dtype)
    inputs = {"Q": [q], "K": [k]}
    if position is not None:
        inputs["Position"] = [position]
    helper.append_op(
        type="rotary_embedding",
        inputs=inputs,
        outputs={"QOut": [q_out], "KOut": [k_out]},
        attrs={"base": float(base)},
    )
    return q_out, k_out


def paged_attention(query, k_pool, v_pool, page_table, lengths,
                    sm_scale=None, impl="auto", name=None):
    """Ragged paged-attention decode (kernels/paged_attention.py).

    ``query`` [S, H, 1, dh] (one token per slot), ``k_pool``/``v_pool``
    [num_pages, H, page_size, dh], ``page_table`` [S, pages_per_slot]
    int page ids, ``lengths`` [S] (or [S, 1]) resident tokens per slot.
    Per-slot cost is bounded by the slot's OWN length — empty pages and
    unoccupied slots are skipped, so decode traffic scales with tokens
    actually resident, not ``S x max_length``."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(query.dtype)
    helper.append_op(
        type="paged_attention",
        inputs={"Q": [query], "KPool": [k_pool], "VPool": [v_pool],
                "PageTable": [page_table], "Lengths": [lengths]},
        outputs={"Out": [out]},
        attrs={"sm_scale": float(sm_scale or 0.0), "impl": impl},
    )
    return out


def paged_kv_write(k_pool, v_pool, k_new, v_new, page_table, pos,
                   name=None):
    """O(page) KV-pool write: each slot's new K/V row ``[S, H, 1, dh]``
    lands at (``page_table[s, pos // page_size]``, ``pos % page_size``).
    Pass the pool vars as both input and output (the optimizer-style
    in-place state convention): this layer binds ``KOut``/``VOut`` back
    onto the pool vars, so the executor threads the update."""
    helper = LayerHelper("paged_kv_write", name=name)
    helper.append_op(
        type="paged_kv_write",
        inputs={"KPool": [k_pool], "VPool": [v_pool], "KNew": [k_new],
                "VNew": [v_new], "PageTable": [page_table], "Pos": [pos]},
        outputs={"KOut": [k_pool], "VOut": [v_pool]},
    )
    return k_pool, v_pool


def paged_kv_prefill(k_pool, v_pool, k_new, v_new, page_row, write_from,
                     length, name=None):
    """Chunked-prefill KV scatter: land a forced prefix's whole
    ``[1, H, T, dh]`` K/V rows into the slot's pages in one op —
    position ``p`` writes at ``(page_row[p // page_size],
    p % page_size)`` for ``write_from <= p < length - 1``; positions a
    prefix-cache hit already covers, and the pad tail, route to the
    trash page. In-place state convention: binds ``KOut``/``VOut`` back
    onto the pool vars."""
    helper = LayerHelper("paged_kv_prefill", name=name)
    helper.append_op(
        type="paged_kv_prefill",
        inputs={"KPool": [k_pool], "VPool": [v_pool], "KNew": [k_new],
                "VNew": [v_new], "PageRow": [page_row],
                "WriteFrom": [write_from], "Len": [length]},
        outputs={"KOut": [k_pool], "VOut": [v_pool]},
    )
    return k_pool, v_pool


def paged_copy_page(k_pool, v_pool, src_page, dst_page, name=None):
    """On-device page copy (the COW primitive): ``pool[dst] =
    pool[src]`` for both the K and V pool in one op. The serving
    session dispatches this before repointing a forked slot's table
    row at the private copy. In-place state convention on the pool
    vars."""
    helper = LayerHelper("paged_copy_page", name=name)
    helper.append_op(
        type="paged_copy_page",
        inputs={"KPool": [k_pool], "VPool": [v_pool], "Src": [src_page],
                "Dst": [dst_page]},
        outputs={"KOut": [k_pool], "VOut": [v_pool]},
    )
    return k_pool, v_pool


def paged_tree_attention(query, k_pool, v_pool, page_table, base_lens,
                         anc, sm_scale=None, max_length=0, impl="auto",
                         name=None):
    """Speculative tree-verify attention over the paged pool
    (kernels/paged_attention.py ``paged_tree_attention``).

    ``query`` [S, H, N, dh] — N speculation-tree nodes per slot, laid
    out linearly in the slot's write pages at storage positions
    ``base .. base + N - 1``; ``base_lens`` [S] (or [S, 1]) committed
    rows per slot (-1 marks a done slot: output exactly 0); ``anc``
    [S, N, N] ancestor mask (diagonal included). Node ``n`` attends
    every committed row plus its own root path — K speculated tokens
    verified in ONE target dispatch."""
    helper = LayerHelper("paged_tree_attention", name=name)
    out = helper.create_variable_for_type_inference(query.dtype)
    helper.append_op(
        type="paged_tree_attention",
        inputs={"Q": [query], "KPool": [k_pool], "VPool": [v_pool],
                "PageTable": [page_table], "BaseLens": [base_lens],
                "Anc": [anc]},
        outputs={"Out": [out]},
        attrs={"sm_scale": float(sm_scale or 0.0), "impl": impl,
               "max_length": int(max_length)},
    )
    return out


def paged_spec_kv_write(k_pool, v_pool, k_new, v_new, page_table, pos,
                        name=None):
    """Tree write for the speculative verify step: all N tree nodes'
    K/V rows ``[S, H, N, dh]`` land at storage positions ``pos[s] ..
    pos[s] + N - 1`` through the table (rows past the table's coverage
    trash-route). In-place state convention: binds ``KOut``/``VOut``
    back onto the pool vars."""
    helper = LayerHelper("paged_spec_kv_write", name=name)
    helper.append_op(
        type="paged_spec_kv_write",
        inputs={"KPool": [k_pool], "VPool": [v_pool], "KNew": [k_new],
                "VNew": [v_new], "PageTable": [page_table], "Pos": [pos]},
        outputs={"KOut": [k_pool], "VOut": [v_pool]},
    )
    return k_pool, v_pool


def paged_spec_kv_compact(k_pool, v_pool, page_table, pos, path,
                          accept_len, name=None):
    """Survivor commit of the accepted speculation path: storage row
    ``pos + j`` receives tree node ``path[s, j]``'s K/V row for
    ``1 <= j < accept_len[s]`` — rejected branches stay behind past the
    new resident length and are never attended again. In-place state
    convention on the pool vars."""
    helper = LayerHelper("paged_spec_kv_compact", name=name)
    helper.append_op(
        type="paged_spec_kv_compact",
        inputs={"KPool": [k_pool], "VPool": [v_pool],
                "PageTable": [page_table], "Pos": [pos], "Path": [path],
                "AcceptLen": [accept_len]},
        outputs={"KOut": [k_pool], "VOut": [v_pool]},
    )
    return k_pool, v_pool


def grouped_cross_attention(query, k_pool, v_pool, group_of, mask,
                            sm_scale=None, impl="auto", name=None):
    """Group-indexed cross attention for the paged decode step.

    ``query`` [S, H, 1, dh]; ``k_pool``/``v_pool`` [G, H, T_src, dh] —
    one cross K/V row per admitted SOURCE, not per slot; ``group_of``
    [S, 1] (or [S]) int group ids; ``mask`` [G, T_src] validity rows.
    Each slot attends over its group's row, so N slots decoding
    continuations of one source cost one group's HBM instead of N
    dense rows."""
    helper = LayerHelper("grouped_cross_attention", name=name)
    out = helper.create_variable_for_type_inference(query.dtype)
    helper.append_op(
        type="grouped_cross_attention",
        inputs={"Q": [query], "KPool": [k_pool], "VPool": [v_pool],
                "GroupOf": [group_of], "Mask": [mask]},
        outputs={"Out": [out]},
        attrs={"sm_scale": float(sm_scale or 0.0), "impl": impl},
    )
    return out


def slot_decode_sample(logits, pos, done=None, strategy="greedy",
                       temperature=1.0, top_k=0, base_seed=0, eos_id=2,
                       max_length=0, name=None):
    """Per-slot token selection + slot lifecycle step for the decode
    loop: sample (greedy / temperature / top-k; PRNG keyed on
    ``(base_seed, slot, position)`` so seeded replays are bit-identical
    at any dispatch granularity), force eos on finished slots, advance
    positions with the max-length clamp, latch the done flag. Returns
    ``(token [S, 1], new_pos [S, 1], new_done [S, 1])``.
    ``max_length`` is the decode budget (the slot pool's ``T``) and is
    REQUIRED: the position clamp is ``min(pos + 1, max_length - 1)``,
    so an unset budget would pin every slot to position -1."""
    if int(max_length) < 2:
        raise ValueError(
            "slot_decode_sample needs max_length >= 2 (the decode "
            "budget; positions clamp to max_length - 1), got %r"
            % (max_length,))
    if strategy == "top_k" and int(top_k) < 1:
        raise ValueError(
            "slot_decode_sample strategy 'top_k' needs top_k >= 1 — "
            "0 would silently sample the full vocabulary")
    helper = LayerHelper("slot_decode_sample", name=name)
    tok = helper.create_variable_for_type_inference("int64")
    new_pos = helper.create_variable_for_type_inference("int64")
    new_done = helper.create_variable_for_type_inference("int64")
    inputs = {"Logits": [logits], "Pos": [pos]}
    if done is not None:
        inputs["Done"] = [done]
    helper.append_op(
        type="slot_decode_sample",
        inputs=inputs,
        outputs={"Out": [tok], "PosOut": [new_pos], "DoneOut": [new_done]},
        attrs={"strategy": strategy, "temperature": float(temperature),
               "top_k": int(top_k), "base_seed": int(base_seed),
               "eos_id": int(eos_id), "max_length": int(max_length)},
    )
    return tok, new_pos, new_done


def slot_beam_search(logits, tok, pos, done, score, beam_width,
                     eos_id=2, max_length=0, name=None):
    """Batched beam selection + parent gather over the slot pool
    (``ops/beam_search_ops.py`` ``slot_beam_search``): the ``S = B*K``
    slots are K-wide beam LANES; one ``lax.top_k`` lattice per lane
    selects survivors, and each survivor adopts its parent's
    position/done state in-graph — the session gathers the page-table
    rows by the returned GLOBAL parent indices, so a hypothesis reorder
    moves table rows and refcounts, never KV bytes. Returns ``(token,
    new_pos, new_done, new_score, parent)`` — all ``[S, 1]``."""
    if int(beam_width) < 2:
        raise ValueError(
            "slot_beam_search needs beam_width >= 2 (width 1 is "
            "slot_decode_sample's job), got %r" % (beam_width,))
    if int(max_length) < 2:
        raise ValueError(
            "slot_beam_search needs max_length >= 2 (the decode "
            "budget), got %r" % (max_length,))
    helper = LayerHelper("slot_beam_search", name=name)
    tok_out = helper.create_variable_for_type_inference("int64")
    new_pos = helper.create_variable_for_type_inference("int64")
    new_done = helper.create_variable_for_type_inference("int64")
    new_score = helper.create_variable_for_type_inference("float32")
    parent = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="slot_beam_search",
        inputs={"Logits": [logits], "Tok": [tok], "Pos": [pos],
                "Done": [done], "Score": [score]},
        outputs={"Out": [tok_out], "PosOut": [new_pos],
                 "DoneOut": [new_done], "ScoreOut": [new_score],
                 "ParentOut": [parent]},
        attrs={"beam_width": int(beam_width), "eos_id": int(eos_id),
               "max_length": int(max_length)},
    )
    return tok_out, new_pos, new_done, new_score, parent


def slot_speculative_accept(logits, nodes, parent, pos, done,
                            strategy="greedy", temperature=1.0, top_k=0,
                            base_seed=0, eos_id=2, max_length=0,
                            name=None):
    """In-graph accept/reject walk for speculative decoding
    (``ops/speculative_ops.py``): replay the sequential sampling rule
    down the speculation tree — same token-choice core and
    ``(base_seed, slot, position)`` PRNG keys as ``slot_decode_sample``,
    same ``slot_lifecycle_advance`` formula — and commit the longest
    draft prefix the target itself would emit, plus one correction or
    bonus token. ``logits`` [S, N, V]; ``nodes``/``parent`` [S, N];
    returns ``(anchor_tok [S,1], tok_seq [S,N], accept_len [S,1],
    path [S,N], new_pos [S,1], new_done [S,1])``."""
    if int(max_length) < 2:
        raise ValueError(
            "slot_speculative_accept needs max_length >= 2 (the decode "
            "budget), got %r" % (max_length,))
    if strategy == "top_k" and int(top_k) < 1:
        raise ValueError(
            "slot_speculative_accept strategy 'top_k' needs top_k >= 1 "
            "— 0 would silently sample the full vocabulary")
    helper = LayerHelper("slot_speculative_accept", name=name)
    anchor = helper.create_variable_for_type_inference("int64")
    tok_seq = helper.create_variable_for_type_inference("int64")
    accept_len = helper.create_variable_for_type_inference("int64")
    path = helper.create_variable_for_type_inference("int64")
    new_pos = helper.create_variable_for_type_inference("int64")
    new_done = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="slot_speculative_accept",
        inputs={"Logits": [logits], "Nodes": [nodes], "Parent": [parent],
                "Pos": [pos], "Done": [done]},
        outputs={"Out": [anchor], "TokSeq": [tok_seq],
                 "AcceptLen": [accept_len], "Path": [path],
                 "PosOut": [new_pos], "DoneOut": [new_done]},
        attrs={"strategy": strategy, "temperature": float(temperature),
               "top_k": int(top_k), "base_seed": int(base_seed),
               "eos_id": int(eos_id), "max_length": int(max_length)},
    )
    return anchor, tok_seq, accept_len, path, new_pos, new_done


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(label.dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="add_position_encoding",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"alpha": float(alpha), "beta": float(beta)},
    )
    return out


def moe_ffn(
    x,
    num_experts,
    d_hidden,
    top_k=1,
    capacity_factor=1.25,
    act="gelu",
    mask=None,
    param_attr=None,
    name=None,
):
    """Mixture-of-Experts feed-forward block (Switch-Transformer style;
    ops/moe_ops.py). x: [batch, seq, d_model]; returns (out, aux_loss) —
    add ``aux_loss`` (scaled, typically by 1e-2) to the training loss to
    balance expert load. ``mask`` ([batch, seq] validity, 1 = real
    token) keeps padding out of routing: pads consume no expert
    capacity and are excluded from the load-balancing statistics.

    Expert parallelism: shard the stacked expert parameters on dim 0
    over a mesh axis via ParallelExecutor(sharding_overrides=...); GSPMD
    inserts the token all-to-alls.
    """
    import copy

    from paddle_tpu import initializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("moe_ffn", param_attr=param_attr, name=name)
    d_model = int(x.shape[-1])
    e, h = int(num_experts), int(d_hidden)

    def _slot_attr(suffix):
        # Five distinct parameters: a single user-NAMED ParamAttr would
        # otherwise alias them all (create_parameter returns the existing
        # var on name collision), so suffix the name per slot.
        attr = ParamAttr._to_attr(copy.copy(helper.param_attr))
        if getattr(attr, "name", None):
            attr.name = attr.name + "_" + suffix
        return attr

    gate_w = helper.create_parameter(
        attr=_slot_attr("gate"), shape=[d_model, e], dtype=x.dtype)
    w1 = helper.create_parameter(
        attr=_slot_attr("w1"), shape=[e, d_model, h], dtype=x.dtype)
    b1 = helper.create_parameter(
        attr=_slot_attr("b1"), shape=[e, h], dtype=x.dtype,
        default_initializer=initializer.Constant(0.0))
    w2 = helper.create_parameter(
        attr=_slot_attr("w2"), shape=[e, h, d_model], dtype=x.dtype)
    b2 = helper.create_parameter(
        attr=_slot_attr("b2"), shape=[e, d_model], dtype=x.dtype,
        default_initializer=initializer.Constant(0.0))
    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference(x.dtype)
    op_inputs = {"X": [x], "GateW": [gate_w], "ExpertW1": [w1],
                 "ExpertB1": [b1], "ExpertW2": [w2], "ExpertB2": [b2]}
    if mask is not None:
        op_inputs["Mask"] = [mask]
    helper.append_op(
        type="moe_ffn",
        inputs=op_inputs,
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"top_k": int(top_k),
               "capacity_factor": float(capacity_factor), "act": act},
    )
    return out, aux
