"""Metric layers: accuracy, auc (layers/metric_op.py parity)."""

from paddle_tpu import initializer as init_mod
from paddle_tpu import unique_name
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["accuracy", "auc", "precision_recall"]


def accuracy(input, label, k=1, correct=None, total=None):
    from paddle_tpu.layers.nn import topk

    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32",
                                                        stop_gradient=True)
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32",
                                                            stop_gradient=True)
    if total is None:
        total = helper.create_variable_for_type_inference("int32",
                                                          stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        name=unique_name.generate("auc.stat_pos"),
        shape=[num_thresholds],
        dtype="int64",
        persistable=True,
        initializer=init_mod.ConstantInitializer(0),
    )
    stat_neg = helper.create_global_variable(
        name=unique_name.generate("auc.stat_neg"),
        shape=[num_thresholds],
        dtype="int64",
        persistable=True,
        initializer=init_mod.ConstantInitializer(0),
    )
    auc_out = helper.create_variable_for_type_inference("float32",
                                                        stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input],
            "Label": [label],
            "StatPos": [stat_pos],
            "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out],
            "StatPosOut": [stat_pos],
            "StatNegOut": [stat_neg],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]


def precision_recall(input, label, class_number, weights=None):
    """Multi-class precision/recall/F1 with accumulated state
    (precision_recall_op.cc). ``input`` is class probabilities [N, C];
    returns (batch_metrics [6], accum_metrics [6], states [C, 4] persistable)
    where metrics are [macro-P, macro-R, macro-F1, micro-P, micro-R,
    micro-F1] and states accumulate [TP, FP, TN, FN] per class."""
    from paddle_tpu.layers.nn import topk

    helper = LayerHelper("precision_recall")
    max_probs, idx = topk(input, k=1)
    states = helper.create_global_variable(
        name=unique_name.generate("precision_recall.states"),
        shape=[class_number, 4],
        dtype="float32",
        persistable=True,
        initializer=init_mod.ConstantInitializer(0),
    )
    batch_metrics = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    accum_metrics = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    inputs = {
        "MaxProbs": [max_probs],
        "Indices": [idx],
        "Labels": [label],
        "StatesInfo": [states],
    }
    if weights is not None:
        inputs["Weights"] = [weights]
    helper.append_op(
        type="precision_recall",
        inputs=inputs,
        outputs={
            "BatchMetrics": [batch_metrics],
            "AccumMetrics": [accum_metrics],
            "AccumStatesInfo": [states],
        },
        attrs={"class_number": class_number},
    )
    return batch_metrics, accum_metrics, states
