"""Metric layers: accuracy, auc (layers/metric_op.py parity)."""

from paddle_tpu import initializer as init_mod
from paddle_tpu import unique_name
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    from paddle_tpu.layers.nn import topk

    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32",
                                                        stop_gradient=True)
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32",
                                                            stop_gradient=True)
    if total is None:
        total = helper.create_variable_for_type_inference("int32",
                                                          stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        name=unique_name.generate("auc.stat_pos"),
        shape=[num_thresholds],
        dtype="int64",
        persistable=True,
        initializer=init_mod.ConstantInitializer(0),
    )
    stat_neg = helper.create_global_variable(
        name=unique_name.generate("auc.stat_neg"),
        shape=[num_thresholds],
        dtype="int64",
        persistable=True,
        initializer=init_mod.ConstantInitializer(0),
    )
    auc_out = helper.create_variable_for_type_inference("float32",
                                                        stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input],
            "Label": [label],
            "StatPos": [stat_pos],
            "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out],
            "StatPosOut": [stat_pos],
            "StatNegOut": [stat_neg],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]
