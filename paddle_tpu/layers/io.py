"""Data layers + in-graph reader pipeline front-end.

Reference parity: python/paddle/fluid/layers/io.py (data(), py_reader,
double_buffer...). The TPU pipeline: py_reader exposes a host-side
blocking queue (paddle_tpu/reader/queue.py) that the executor drains and
feeds; device-side double-buffering is the executor's async dispatch (XLA
runs ahead while the host prepares the next batch), so decorators are
capability-preserving wrappers instead of graph reader ops.
"""

import time

from paddle_tpu import framework
from paddle_tpu.core.types import VarType
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.observability import step_profiler as _stepprof

__all__ = ["data", "py_reader", "double_buffer", "read_file", "batch",
           "shuffle", "random_data_generator", "open_recordio_file",
           "open_files", "Preprocessor"]


def data(name, shape, dtype="float32", lod_level=0, type=VarType.LOD_TENSOR,
         append_batch_size=True, stop_gradient=True):
    """Declare an input variable (layers/io.py data parity). With
    append_batch_size, a leading -1 batch dim is added as in Fluid."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        type=type,
        stop_gradient=stop_gradient,
        is_data=True,
    )


def random_data_generator(shapes, dtypes, low=0.0, high=1.0, int_low=0,
                          int_high=1, name=None):
    """In-graph synthetic data source: returns one Variable per slot, drawn
    on-device each step by the XLA program (reference capability:
    operators/reader/create_random_data_generator_op.cc, the synthetic
    reader used for IO-free benchmark runs). ``shapes`` include the batch
    dim and must be static. Float slots ~ U[low, high); int slots ~
    U{int_low, int_high} inclusive."""
    helper = LayerHelper("random_data_generator", name=name)
    if len(shapes) != len(dtypes):
        raise ValueError(
            "random_data_generator: %d shapes but %d dtypes"
            % (len(shapes), len(dtypes))
        )
    shape_concat, ranks = [], []
    for s in shapes:
        s = [int(d) for d in s]
        if any(d <= 0 for d in s):
            raise ValueError(
                "random_data_generator needs fully static shapes, got %r" % (s,)
            )
        shape_concat.extend(s)
        ranks.append(len(s))
    outs = []
    for i, (s, dt) in enumerate(zip(shapes, dtypes)):
        outs.append(
            helper.block.create_var(
                name="%s_slot%d" % (helper.name, i),
                shape=[int(d) for d in s],
                dtype=dt,
                stop_gradient=True,
            )
        )
    helper.append_op(
        type="random_data_generator",
        inputs={},
        outputs={"Out": outs},
        attrs={
            "shape_concat": shape_concat,
            "ranks": ranks,
            "dtypes": [str(d) for d in dtypes],
            "min": float(low),
            "max": float(high),
            "int_min": int(int_low),
            "int_max": int(int_high),
        },
    )
    return outs


class PyReader(object):
    """Host queue + feed-var bundle returned by py_reader."""

    def __init__(self, feed_vars, capacity, use_double_buffer=True):
        from paddle_tpu.reader.queue import BlockingQueue

        self.feed_vars = feed_vars
        # Prefer the C++ queue (LoDTensorBlockingQueue parity): producers
        # block in native code instead of a Python condition variable.
        self.queue = None
        try:
            from paddle_tpu import native
            from paddle_tpu.reader.queue import NativeTensorQueue

            if native.prebuilt():
                self.queue = NativeTensorQueue(capacity)
        except Exception:
            pass
        if self.queue is None:
            self.queue = BlockingQueue(capacity)
        self._decorated = None
        self._thread = None
        self._prefetch_q = None
        self._prefetch_thread = None
        self.use_double_buffer = use_double_buffer

    def decorate_paddle_reader(self, reader):
        self._decorated = reader

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_paddle_reader

    def decorate_tensor_provider(self, reader):
        self._decorated = reader

    def decorate_paddle_readers(self, readers, passes=1):
        """Multiple source readers drained by parallel worker threads into
        the one queue (open_files_op.cc thread_num capability). Sample
        order interleaves arbitrarily across sources WITHIN a pass;
        passes are synchronized — every source finishes pass k before any
        source starts pass k+1 (upstream multi_pass semantics)."""
        readers = list(readers)
        if not readers:
            raise ValueError("decorate_paddle_readers needs >= 1 reader")
        self._decorated = readers
        self._passes = max(1, int(passes))

    def start(self, place=None):
        """Begin draining the decorated reader into the queue. With
        ``use_double_buffer`` and a ``place``, a prefetch stage
        additionally moves batches to the device AHEAD of consumption
        (buffered_reader.h:27 capability): ``jax.device_put`` is async, so
        the host->device copy of batch k+1 overlaps compute on batch k,
        and next_feed() hands back device arrays the executor feeds
        without another transfer."""
        import threading

        if self._decorated is None:
            raise RuntimeError("no reader decorated onto py_reader")
        if not isinstance(self._decorated, list):
            sources, passes = [self._decorated], 1
        else:
            sources, passes = self._decorated, getattr(self, "_passes", 1)
        self.queue.reopen()
        self._worker_error = None

        # one coordinator drives `passes` barrier-synchronized rounds of
        # shard workers; a worker exception is recorded and surfaced from
        # next_feed() instead of masquerading as a clean EOF
        def _worker(src):
            try:
                for item in src():
                    if not self.queue.push(item):
                        return
            except BaseException as e:  # noqa: BLE001 - resurfaced in next_feed
                self._worker_error = e
                self.queue.kill()

        def _coordinator():
            for _ in range(passes):
                threads = [
                    threading.Thread(target=_worker, args=(src,),
                                     daemon=True,
                                     name="paddle-tpu-feed-shard-%d" % i)
                    for i, src in enumerate(sources)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if self._worker_error is not None:
                    return
            self.queue.close()

        self._thread = threading.Thread(target=_coordinator, daemon=True,
                                        name="paddle-tpu-feed-coord")
        self._thread.start()
        if self.use_double_buffer and place is not None:
            self._start_prefetch(place)

    def _start_prefetch(self, place):
        """Double buffer: a host thread pops batches and device_puts them
        up to 2 deep; the async transfer rides under the previous step's
        compute instead of serializing in front of it."""
        import queue as pyqueue
        import threading

        import jax
        import numpy as np

        device = place.jax_device()
        self._prefetch_q = pyqueue.Queue(maxsize=2)
        pq = self._prefetch_q

        def _prefetcher():
            try:
                while True:
                    item = self.queue.pop()
                    if item is None:
                        pq.put(None)
                        return
                    feed = self._to_feed_dict(item)
                    feed = {
                        k: jax.device_put(np.asarray(v), device)
                        for k, v in feed.items()
                    }
                    if not self._pq_put(pq, feed):
                        return
            except BaseException as e:  # noqa: BLE001 - resurfaced in next_feed
                # a device_put/conversion failure must not strand the
                # consumer on pq.get() forever: record + sentinel
                self._worker_error = e
                self.queue.kill()
                pq.put(None)

        self._prefetch_thread = threading.Thread(
            target=_prefetcher, daemon=True,
            name="paddle-tpu-feed-prefetch")
        self._prefetch_thread.start()

    def _pq_put(self, pq, feed):
        """Bounded put that gives up when the reader is reset (the consumer
        is gone; blocking forever would leak the thread)."""
        import queue as pyqueue

        while pq is self._prefetch_q:
            try:
                pq.put(feed, timeout=0.2)
                return True
            except pyqueue.Full:
                continue
        return False

    def _to_feed_dict(self, item):
        if isinstance(item, dict):
            return item
        return {v.name: arr for v, arr in zip(self.feed_vars, item)}

    def reset(self):
        self.queue.kill()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        pq = getattr(self, "_prefetch_q", None)
        self._prefetch_q = None
        if pq is not None:
            while True:  # drain so a blocked prefetcher sees the reset
                try:
                    pq.get_nowait()
                except Exception:
                    break
            self._prefetch_thread.join(timeout=5)
            self._prefetch_thread = None
        self._worker_error = None

    def next_feed(self):
        """Pop one batch -> feed dict (device arrays when the prefetch
        stage is on); raises EOFException at end, or the reader thread's
        exception if one died mid-stream."""
        pq = getattr(self, "_prefetch_q", None)
        if _stepprof.ENABLED:
            # consumer-side starvation, measured at the source: this
            # blocking get/pop is the training thread waiting on the
            # input pipeline, banked as the next step's input_wait phase
            t0 = time.monotonic()
            item = pq.get() if pq is not None else self.queue.pop()
            _stepprof.note_input_wait(time.monotonic() - t0,
                                      site="py_reader")
        else:
            item = pq.get() if pq is not None else self.queue.pop()
        if item is None:
            if pq is not None:
                # keep the sentinel: a second post-EOF next_feed() must
                # raise again, not block (matches the unbuffered path,
                # where pop() on a closed queue keeps returning None)
                pq.put(None)
            err = getattr(self, "_worker_error", None)
            if err is not None:
                raise RuntimeError("py_reader source failed") from err
            from paddle_tpu.reader.queue import EOFException

            raise EOFException()
        return self._to_feed_dict(item)


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Create feed vars + a host blocking-queue reader
    (create_py_reader_op.cc + LoDTensorBlockingQueue capability)."""
    from paddle_tpu import unique_name

    lod_levels = lod_levels or [0] * len(shapes)
    feed_vars = []
    for i, (shape, dtype, lod) in enumerate(zip(shapes, dtypes, lod_levels)):
        feed_vars.append(
            data(
                name=unique_name.generate((name or "py_reader") + "_slot%d" % i),
                shape=list(shape)[1:],
                dtype=dtype,
                lod_level=lod,
                append_batch_size=True,
            )
        )
    return PyReader(feed_vars, capacity, use_double_buffer)


def double_buffer(reader, place=None, name=None):
    """Device prefetch decorator: on TPU the executor overlaps host feed
    with device compute via async dispatch; kept for API parity."""
    return reader


def read_file(reader):
    if isinstance(reader, PyReader):
        return reader.feed_vars
    return reader


def batch(reader, batch_size):
    from paddle_tpu.reader import decorator

    return decorator.batch(reader, batch_size)


def shuffle(reader, buffer_size):
    from paddle_tpu.reader import decorator

    return decorator.shuffle(reader, buffer_size)


def open_recordio_file(filename, shapes, dtypes, lod_levels=None,
                       pass_num=1, for_parallel=False, capacity=64,
                       name=None):
    """Graph-level recordio reader (create_recordio_file_reader_op.cc
    role): returns a PyReader whose worker thread streams records from
    the native recordio reader into the C++ blocking queue — the
    file->queue->device pipeline, with the file parsing in the reader
    thread instead of an in-graph op (XLA programs cannot do file I/O;
    the queue hop is where the reference's DecoratedReader chain ran)."""
    return open_files([filename], shapes, dtypes, lod_levels=lod_levels,
                      pass_num=pass_num, capacity=capacity, name=name)


def open_files(filenames, shapes, dtypes, thread_num=1, buffer_size=None,
               lod_levels=None, pass_num=1, capacity=64, name=None):
    """Multi-file recordio reader (open_files_op.cc role). With
    thread_num > 1 the files are split round-robin across that many
    reader threads all feeding the one blocking queue (records then
    interleave across files, as in the reference); with one thread files
    are consumed in order per pass. ``buffer_size`` maps onto the queue
    capacity. Shuffle with the reader decorators."""
    from paddle_tpu import native
    from paddle_tpu.recordio_writer import unpack_sample

    filenames = list(filenames)  # accept any iterable of paths
    reader = py_reader(buffer_size or capacity, shapes, dtypes,
                       lod_levels=lod_levels, name=name or "open_files")

    def make_source(paths, n_passes=1):
        def source():
            for _ in range(n_passes):
                for path in paths:
                    with native.RecordIOReader(path) as r:
                        for blob in r:
                            yield unpack_sample(blob)

        return source

    n_threads = max(1, min(int(thread_num or 1), len(filenames)))
    if n_threads == 1:
        reader.decorate_paddle_reader(make_source(list(filenames), pass_num))
    else:
        shards = [list(filenames[i::n_threads]) for i in range(n_threads)]
        reader.decorate_paddle_readers(
            [make_source(s) for s in shards], passes=pass_num)
    return reader


class Preprocessor(object):
    """In-graph reader preprocessing (layers/io.py Preprocessor parity).

    The reference builds a separate sub-block executed by a
    create_custom_reader op; here the transform layers are ordinary ops
    in the main block operating on the reader's output vars (the XLA
    program fuses them with the model), so ``block()`` only brackets the
    definition and validates the protocol.

    Usage::

        pre = fluid.layers.Preprocessor(reader=py_reader_obj)
        with pre.block():
            img, label = pre.inputs()
            pre.outputs(fluid.layers.scale(img, 1. / 255), label)
        img, label = pre()
    """

    def __init__(self, reader, name=None):
        self._reader = reader
        self._outputs = None
        self._in_block = False

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            self._in_block = True
            try:
                yield self
            finally:
                self._in_block = False
            # only after a clean exit: an exception from user code inside
            # the block must propagate, not be masked by this check
            if self._outputs is None:
                raise RuntimeError(
                    "Preprocessor.block() ended without outputs(); "
                    "call pre.outputs(...) inside the block")

        return guard()

    def inputs(self):
        if not self._in_block:
            raise RuntimeError("Preprocessor.inputs() outside block()")
        return read_file(self._reader)

    def outputs(self, *outs):
        if not self._in_block:
            raise RuntimeError("Preprocessor.outputs() outside block()")
        self._outputs = list(outs)

    def __call__(self):
        if self._outputs is None:
            raise RuntimeError("Preprocessor was never defined via block()")
        return self._outputs
