"""Core NN layers (python/paddle/fluid/layers/nn.py parity — the 134
hand-written layers; first waves cover the benchmark models' surface).
"""

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "dynamic_update_slice",
    "fc",
    "embedding",
    "dropout",
    "softmax",
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "depthwise_conv2d",
    "pool2d",
    "pool3d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "lrn",
    "mul",
    "matmul",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "mean",
    "scale",
    "reshape",
    "transpose",
    "split",
    "squeeze",
    "unsqueeze",
    "flatten",
    "stack",
    "unstack",
    "expand",
    "slice",
    "shape",
    "gather",
    "batched_gather",
    "scatter",
    "pad",
    "pad2d",
    "one_hot",
    "topk",
    "l2_normalize",
    "prelu",
    "relu",
    "log",
    "image_resize",
    "resize_bilinear",
    "im2sequence",
    "cos_sim",
    "affine_channel",
    "affine_grid",
    "grid_sampler",
    "multiplex",
    "bilinear_tensor_product",
    "mean_iou",
    "hash",
    "lod_reset",
    "fake_quantize_abs_max",
    "conv3d_transpose",
    "Print",
    "random_crop",
    "dice_loss",
    "image_resize_short",
    "autoincreased_step_counter",
    "sequence_expand",
]

from paddle_tpu.layers.ops import relu, log  # noqa: E402,F401  (re-export)


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    is_test=False,
    name=None,
):
    """Fully-connected layer (layers/nn.py fc parity): mul per input +
    optional multi-input sum + bias + activation. On TPU the mul lowers
    straight onto the MXU."""
    helper = LayerHelper(
        "fc", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = helper.param_attr
    if not isinstance(param_attrs, (list, tuple)):
        param_attrs = [param_attrs] * len(inputs)

    mul_results = []
    for inp, attr in zip(inputs, param_attrs):
        input_shape = inp.shape
        in_features = 1
        for d in input_shape[num_flatten_dims:]:
            in_features *= int(d)
        w = helper.create_parameter(
            attr=attr, shape=[in_features, size], dtype=inp.dtype
        )
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """lookup_table layer. On TPU, sharded-huge-table capability comes from
    GSPMD row-sharding of W over the mesh (parallel/ api), replacing the
    reference's pserver prefetch path (lookup_table_op.cc:71-75)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=list(size), dtype=dtype, is_bias=False
    )
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1
        if padding_idx is None
        else padding_idx
        if padding_idx >= 0
        else (size[0] + padding_idx)
    )
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    return out


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "fix_seed": seed is not None,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="softmax", inputs={"X": [input]}, outputs={"Out": [out]}
    )
    return out


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    helper = LayerHelper(
        "conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    groups = groups or 1
    num_channels = int(input.shape[1])
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    import math

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    from paddle_tpu import initializer as init_mod

    std = math.sqrt(2.0 / fan_in)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, std),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper(
        "conv3d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    groups = groups or 1
    num_channels = int(input.shape[1])

    def _triple(v):
        return [v, v, v] if isinstance(v, int) else list(v)

    filter_size = _triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": _triple(stride),
            "paddings": _triple(padding),
            "dilations": _triple(dilation),
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def depthwise_conv2d(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    helper = LayerHelper(
        "depthwise_conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act,
        name=name,
    )
    num_channels = int(input.shape[1])
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, 1] + list(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="depthwise_conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride, stride] if isinstance(stride, int) else stride,
            "paddings": [padding, padding] if isinstance(padding, int) else padding,
            "dilations": [dilation, dilation] if isinstance(dilation, int) else dilation,
            "groups": num_channels,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper(
        "conv2d_transpose", param_attr=param_attr, bias_attr=bias_attr, act=act,
        name=name,
    )
    groups = groups or 1
    num_channels = int(input.shape[1])
    if filter_size is None:
        raise ValueError("filter_size must be given for conv2d_transpose")
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    if output_size is not None and isinstance(output_size, int):
        output_size = [output_size, output_size]
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": [stride, stride] if isinstance(stride, int) else stride,
            "paddings": [padding, padding] if isinstance(padding, int) else padding,
            "dilations": [dilation, dilation] if isinstance(dilation, int) else dilation,
            "groups": groups,
            "output_size": list(output_size or []),
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": [pool_size, pool_size]
            if isinstance(pool_size, int)
            else list(pool_size),
            "strides": [pool_stride, pool_stride]
            if isinstance(pool_stride, int)
            else list(pool_stride),
            "paddings": [pool_padding, pool_padding]
            if isinstance(pool_padding, int)
            else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
):
    """BN layer with running-stat state vars (layers/nn.py batch_norm
    parity). MeanOut/VarianceOut rebind the same persistable vars — the
    executor's functional state threading realizes the in-place update."""
    from paddle_tpu import initializer as init_mod
    from paddle_tpu import unique_name

    helper = LayerHelper(
        "batch_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    channels = int(
        input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    )
    scale = helper.create_parameter(
        attr=helper.param_attr,
        shape=[channels],
        dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr or ParamAttr(), shape=[channels], dtype=dtype,
        is_bias=True,
    )
    mean = helper.create_global_variable(
        name=moving_mean_name or unique_name.generate(helper.name + ".mean"),
        shape=[channels],
        dtype=dtype,
        persistable=True,
        initializer=init_mod.ConstantInitializer(0.0),
    )
    variance = helper.create_global_variable(
        name=moving_variance_name or unique_name.generate(helper.name + ".var"),
        shape=[channels],
        dtype=dtype,
        persistable=True,
        initializer=init_mod.ConstantInitializer(1.0),
    )
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    from paddle_tpu import initializer as init_mod
    import numpy as np

    helper = LayerHelper(
        "layer_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    norm_size = int(np.prod([int(d) for d in input.shape[begin_norm_axis:]]))
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=[norm_size],
            dtype=dtype,
            default_initializer=init_mod.ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr or ParamAttr(), shape=[norm_size], dtype=dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    from paddle_tpu import initializer as init_mod

    helper = LayerHelper(
        "group_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    channels = int(input.shape[1])
    inputs = {"X": [input]}
    s = helper.create_parameter(
        attr=helper.param_attr, shape=[channels], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(1.0),
    )
    b = helper.create_parameter(
        attr=helper.bias_attr or ParamAttr(), shape=[channels], dtype=dtype,
        is_bias=True,
    )
    inputs["Scale"], inputs["Bias"] = [s], [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "groups": groups},
    )
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="lrn",
        inputs={"X": [input]},
        outputs={"Out": [out], "MidOut": [mid]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def _elementwise_layer(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        from paddle_tpu.layers.math_ops import elementwise_binary

        return elementwise_binary(op_type, x, y, axis=axis, act=act, name=name)

    fn.__name__ = op_type
    return fn


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")
elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")


def _reduce_layer(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            attrs = {
                "dim": [dim] if isinstance(dim, int) else list(dim),
                "keep_dim": keep_dim,
                "reduce_all": False,
            }
        helper.append_op(
            type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    fn.__name__ = op_type
    return fn


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reshape",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="transpose",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    from paddle_tpu.ops.common import normalize_axis

    helper = LayerHelper("split", name=name)
    ndim = len(input.shape)
    dim = normalize_axis(dim, ndim, "split dim")
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    n_outs = num if num else len(sections)
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in range(n_outs)
    ]
    helper.append_op(
        type="split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "num": num, "sections": sections},
    )
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="squeeze",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="unsqueeze",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="flatten",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        type="stack", inputs={"X": x}, outputs={"Y": [out]}, attrs={"axis": axis}
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = int(x.shape[axis])
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(
        type="unstack",
        inputs={"X": [x]},
        outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(type="shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def dynamic_update_slice(x, update, index, axis=0, out=None, name=None):
    """Write ``update`` into ``x`` at position ``index`` (a [1] int
    tensor) along ``axis`` — the KV-cache write primitive (XLA
    dynamic-update-slice). Pass ``out=x`` bound to a persistable var to
    get the in-place state-update form the executor threads across
    runs (the optimizer-op convention)."""
    helper = LayerHelper("dynamic_update_slice", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="dynamic_update_slice",
        inputs={"X": [x], "Update": [update], "Index": [index]},
        outputs={"Out": [out]},
        attrs={"axis": int(axis)},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "paddings": list(paddings),
            "mode": mode,
            "pad_value": float(pad_value),
            "data_format": data_format,
        },
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def prelu(x, mode, param_attr=None, name=None):
    from paddle_tpu import initializer as init_mod

    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [int(x.shape[1])]
    else:
        alpha_shape = [int(d) for d in x.shape[1:]]
    alpha = helper.create_parameter(
        attr=helper.param_attr,
        shape=alpha_shape,
        dtype=x.dtype,
        default_initializer=init_mod.ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None):
    helper = LayerHelper("image_resize", name=name)
    if out_shape is None:
        h = int(int(input.shape[2]) * scale)
        w = int(int(input.shape[3]) * scale)
    else:
        h, w = int(out_shape[0]), int(out_shape[1])
    op_type = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": h, "out_w": w},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "BILINEAR", name)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)

    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    p = _pair(padding)
    if len(p) == 2:
        p = p + p
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="im2sequence",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"kernels": _pair(filter_size), "strides": _pair(stride),
               "paddings": p},
    )
    return out


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity (cos_sim_op.cc); Y may be [1, D]."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        type="cos_sim",
        inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def batched_gather(input, index):
    """Per-batch gather along dim 1: out[n, s] = input[n, index[n, s]].
    Negative indices (padding) clamp to row 0 — mask via the caller's
    weights. TPU-friendly take_along_axis, no LoD offsets."""
    helper = LayerHelper("batched_gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="batched_gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    """NCDHW 3D pooling (pool_op.cc pool3d registration)."""
    def _t(v):
        return [v, v, v] if isinstance(v, int) else list(v)
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _t(pool_size),
            "strides": _t(pool_stride),
            "paddings": _t(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """Per-channel affine (affine_channel_op.cc): out = scale_c * x + bias_c.
    The conv+frozen-BN idiom of detection backbones. When scale/bias are
    not given, per-channel parameters are created (initialized to 1 / 0,
    i.e. identity until trained)."""
    helper = LayerHelper("affine_channel", name=name)
    channels = int(x.shape[1] if data_layout == "NCHW" else x.shape[-1])
    if scale is None:
        from paddle_tpu import initializer as init_mod
        scale = helper.create_parameter(
            attr=None, shape=[channels], dtype=x.dtype,
            default_initializer=init_mod.ConstantInitializer(1.0),
        )
    if bias is None:
        bias = helper.create_parameter(
            attr=None, shape=[channels], dtype=x.dtype, is_bias=True,
        )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="affine_channel",
        inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
        outputs={"Out": [out]},
        attrs={"data_layout": data_layout},
    )
    return out


def affine_grid(theta, out_shape, name=None):
    """Affine sampling grid for a spatial transformer
    (affine_grid_op.cc); out_shape must be static under XLA."""
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    if not isinstance(out_shape, (list, tuple)):
        raise TypeError("affine_grid: out_shape must be a static list/tuple "
                        "(XLA needs static shapes)")
    helper.append_op(
        type="affine_grid",
        inputs={"Theta": [theta]},
        outputs={"Output": [out]},
        attrs={"output_shape": list(out_shape)},
    )
    return out


def grid_sampler(x, grid, name=None):
    """Bilinear sampling of x at normalized grid coords
    (grid_sampler_op.cc)."""
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="grid_sampler",
        inputs={"X": [x], "Grid": [grid]},
        outputs={"Output": [out]},
    )
    return out


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (multiplex_op.cc)."""
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(
        type="multiplex",
        inputs={"Ids": [index], "X": list(inputs)},
        outputs={"Out": [out]},
    )
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x^T W_k y (bilinear_tensor_product_op.cc) with learned
    [size, Mx, My] weight and optional bias/activation."""
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[size, x.shape[-1], y.shape[-1]],
        dtype=x.dtype,
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.bias_attr is not None:
        bias = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, size], dtype=x.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [bias]
    helper.append_op(
        type="bilinear_tensor_product",
        inputs=inputs,
        outputs={"Out": [out]},
    )
    return helper.append_activation(out)


def mean_iou(input, label, num_classes, name=None):
    """Segmentation mean-IoU (mean_iou_op.cc): returns (mean_iou, wrong,
    correct) for streaming accumulation."""
    helper = LayerHelper("mean_iou", name=name)
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": num_classes},
    )
    return miou, wrong, correct


def hash(input, hash_size, num_hash=1, name=None):
    """num_hash integer hashes per input row, mod hash_size
    (hash_op.cc)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="hash",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"num_hash": num_hash, "mod_by": hash_size},
    )
    return out


def lod_reset(x, target_lod=None, name=None):
    """Re-segment a padded sequence batch (lod_reset_op.cc). Returns
    (out, length): the re-chunked [B', T', ...] tensor plus its Length
    column for downstream sequence ops (the padded-design carrier of the
    LoD the reference mutates in place — docs/LOD_DESIGN.md). The
    reference's reset-from-Y's-lod form is obviated: under XLA the new
    segmentation must be static, so it is always the target_lod attr."""
    if not target_lod:
        raise ValueError(
            "lod_reset: target_lod is required (the reference's "
            "runtime-Y segmenter cannot exist under static XLA shapes)")
    helper = LayerHelper("lod_reset", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_reset",
        inputs={"X": [x]},
        outputs={"Out": [out], "Length": [length]},
        attrs={"target_lod": list(target_lod)},
    )
    return out, length


def fake_quantize_abs_max(x, bit_length=8, name=None):
    """QAT fake-quantization (fake_quantize_op.cc): returns (quantized,
    scale); gradients pass straight through the rounding."""
    helper = LayerHelper("fake_quantize_abs_max", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    scale = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fake_quantize_abs_max",
        inputs={"X": [x]},
        outputs={"Out": [out], "OutScale": [scale]},
        attrs={"bit_length": bit_length},
    )
    return out, scale


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None, name=None):
    """3D transposed convolution (conv_transpose_op.cc conv3d_transpose)."""
    helper = LayerHelper(
        "conv3d_transpose", param_attr=param_attr, bias_attr=bias_attr,
        act=act, name=name,
    )
    groups = groups or 1
    num_channels = int(input.shape[1])
    if filter_size is None:
        raise ValueError("filter_size must be given for conv3d_transpose")
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype
    )
    out = helper.create_variable_for_type_inference(input.dtype)

    def _t(v):
        return [v, v, v] if isinstance(v, int) else list(v)

    if output_size is not None and isinstance(output_size, int):
        output_size = [output_size] * 3
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": _t(stride),
            "paddings": _t(padding),
            "dilations": _t(dilation),
            "groups": groups,
            "output_size": list(output_size or []),
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both", name=None):
    """Debug print of a tensor at execution time (print_op.cc surface;
    lowers to jax.debug.print inside the compiled step)."""
    helper = LayerHelper("print", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print",
        inputs={"In": [input]},
        outputs={"Out": [out]},
        attrs={"message": message or input.name},
    )
    return out


def random_crop(x, shape, seed=None, name=None):
    """Random spatial crop to `shape` (random_crop_op.cc). The reference
    threads an explicit Seed tensor; here the op draws from the program's
    stateless PRNG stream, and `seed` pins it via a constant."""
    from paddle_tpu.layers import tensor as tensor_layers

    helper = LayerHelper("random_crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    seed_var = tensor_layers.fill_constant(
        shape=[1], dtype="int64", value=int(seed or 0))
    seed_out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="random_crop",
        inputs={"X": [x], "Seed": [seed_var]},
        outputs={"Out": [out], "SeedOut": [seed_out]},
        # nonzero seed pins the op's PRNG stream (fix_seed semantics in
        # core/op_registry.LowerContext.rng)
        attrs={"shape": list(shape), "seed": int(seed or 0)},
    )
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice coefficient loss for segmentation (layers/nn.py dice_loss
    parity): integer class-index labels are one-hot encoded over the last
    dim of `input` as in the reference; float labels are taken as masks
    directly. Reduces over the last dim, then means over samples."""
    from paddle_tpu.layers import tensor as tensor_layers

    if str(label.dtype).startswith("int"):
        label = one_hot(label, depth=int(input.shape[-1]))
        if len(label.shape) > len(input.shape):
            label = squeeze(label, axes=[len(input.shape) - 1])
    label = tensor_layers.cast(label, input.dtype)
    reduce_dim = len(input.shape) - 1
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dim)
    dice_denominator = elementwise_add(
        reduce_sum(input, dim=reduce_dim),
        reduce_sum(label, dim=reduce_dim),
    )
    dice_score = scale(
        elementwise_div(
            scale(inse, scale=2.0),
            elementwise_add(
                dice_denominator,
                tensor_layers.fill_constant([1], input.dtype, epsilon),
            ),
        ),
        scale=-1.0, bias=1.0,
    )
    return reduce_mean(dice_score)


def image_resize_short(input, out_short_len, resample="BILINEAR",
                       name=None):
    """Resize so the SHORT image side equals out_short_len, keeping the
    aspect ratio (layers/nn.py image_resize_short parity)."""
    in_h, in_w = int(input.shape[2]), int(input.shape[3])
    # int(x + 0.5), not round(): matches the reference's half-up rounding
    # (Python round() is banker's and differs on exact .5 ratios)
    if in_h < in_w:
        out_h = out_short_len
        out_w = int(in_w * out_short_len / float(in_h) + 0.5)
    else:
        out_w = out_short_len
        out_h = int(in_h * out_short_len / float(in_w) + 0.5)
    return image_resize(input, out_shape=[out_h, out_w], resample=resample,
                        name=name)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """A persistable int step counter incremented once per run
    (layers/nn.py autoincreased_step_counter parity; the LR schedulers
    share the same counter machinery)."""
    from paddle_tpu.layers import learning_rate_scheduler as lrs

    return lrs._global_step_counter(
        counter_name=counter_name or "@STEP_COUNTER@", begin=begin,
        step=step)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x across y's time dimension then flatten
    (sequence_expand_op.cc, padded-design form: y supplies max_len)."""
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"ref_level": ref_level},
    )
    return out
