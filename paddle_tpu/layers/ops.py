"""Auto-generated thin layer wrappers for registered single-in/single-out ops.

Reference parity: python/paddle/fluid/layers/ops.py +
layer_function_generator.py:122 — layer functions generated from op schemas
(our registry plays the OpProtoHolder role).
"""

from paddle_tpu.core import op_registry
from paddle_tpu.layer_helper import LayerHelper

_UNARY_ACTIVATIONS = [
    "sigmoid",
    "logsigmoid",
    "exp",
    "tanh",
    "tanh_shrink",
    "softshrink",
    "sqrt",
    "rsqrt",
    "abs",
    "ceil",
    "floor",
    "cos",
    "sin",
    "round",
    "reciprocal",
    "log",
    "square",
    "softplus",
    "softsign",
    "relu",
    "relu6",
    "gelu",
    "elu",
    "leaky_relu",
    "soft_relu",
    "brelu",
    "pow",
    "stanh",
    "hard_sigmoid",
    "hard_shrink",
    "thresholded_relu",
    "swish",
    "sign",
    "log_softmax",
]

__all__ = list(_UNARY_ACTIVATIONS) + [
    "uniform_random",
    "gaussian_random",
    "sampling_id",
    "cumsum",
    "clip",
    "clip_by_norm",
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
    "maxout",
]


def _make_unary(op_type):
    opdef = op_registry.get_op_def(op_type)

    def layer_fn(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        attrs = {k: kwargs[k] for k in opdef.attrs if k in kwargs}
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = "Generated layer for operator %r (TPU/XLA lowering)." % op_type
    return layer_fn


for _name in _UNARY_ACTIVATIONS + ["cumsum", "clip", "clip_by_norm", "maxout"]:
    globals()[_name] = _make_unary(_name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "min": min, "max": max,
               "seed": seed},
    )
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "mean": mean, "std": std,
               "seed": seed},
    )
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sampling_id",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"min": min, "max": max, "seed": seed},
    )
    return out


def _make_binary_logical(op_type):
    def layer_fn(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference("bool")
        inputs = {"X": [x]}
        if y is not None:
            inputs["Y"] = [y]
        helper.append_op(type=op_type, inputs=inputs, outputs={"Out": [out]})
        return out

    layer_fn.__name__ = op_type
    return layer_fn


logical_and = _make_binary_logical("logical_and")
logical_or = _make_binary_logical("logical_or")
logical_xor = _make_binary_logical("logical_xor")
logical_not = _make_binary_logical("logical_not")


def fill(shape, value, dtype="float32", name=None):
    """Materialize an explicit value list (fill_op.cc)."""
    helper = LayerHelper("fill", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "value": [float(v) for v in value],
               "dtype": dtype},
    )
    return out


def _make_batch_size_like(op_type, extra):
    def layer_fn(input, shape, input_dim_idx=0, output_dim_idx=0,
                 dtype="float32", name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype)
        attrs = {"shape": list(shape), "input_dim_idx": input_dim_idx,
                 "output_dim_idx": output_dim_idx, "dtype": dtype}
        for k, dv in extra.items():
            attrs[k] = kwargs.get(k, dv)
        helper.append_op(
            type=op_type,
            inputs={"Input": [input]},
            outputs={"Out": [out]},
            attrs=attrs,
        )
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = (
        "Generated layer for operator %r: output shape follows the "
        "input's batch dimension (batch_size_like_op.h role)." % op_type)
    return layer_fn


gaussian_random_batch_size_like = _make_batch_size_like(
    "gaussian_random_batch_size_like", {"mean": 0.0, "std": 1.0, "seed": 0})
uniform_random_batch_size_like = _make_batch_size_like(
    "uniform_random_batch_size_like", {"min": -1.0, "max": 1.0, "seed": 0})

__all__ += ["fill", "gaussian_random_batch_size_like",
            "uniform_random_batch_size_like"]
