"""Loss layers (parts of layers/nn.py + layers/detection.py in fluid)."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "cross_entropy",
    "softmax_with_cross_entropy",
    "fused_label_smooth_ce",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "huber_loss",
    "log_loss",
    "hinge_loss",
    "rank_loss",
    "margin_rank_loss",
    "kldiv_loss",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={
            "soft_label": soft_label,
            "ignore_index": ignore_index,
            "numeric_stable_mode": numeric_stable_mode,
        },
    )
    if return_softmax:
        return loss, softmax
    return loss


def fused_label_smooth_ce(logits, label, epsilon=0.0, name=None):
    """Label-smoothed cross entropy in ONE fused pass over the vocab dim
    (ops/loss_ops.py fused_label_smooth_ce): factored smoothing — no
    soft-label tensor, no second log-softmax pass — with the logits kept
    in their network dtype (bf16 under AMP) and f32-accumulated
    reductions. Returns f32 [N, 1] loss. The MFU lever-#1 form of the
    composed softmax_with_cross_entropy + log_softmax head
    (docs/MFU_PLAN.md); enable in the bundled transformer with
    FLAGS_fused_ce=1."""
    helper = LayerHelper("fused_label_smooth_ce", name=name)
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="fused_label_smooth_ce",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Loss": [loss]},
        attrs={"epsilon": float(epsilon)},
    )
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    """(input - label)^2, elementwise (square_error_cost parity)."""
    helper = LayerHelper("square_error_cost")
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [diff]},
        attrs={"axis": -1},
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square", inputs={"X": [diff]}, outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(input.dtype,
                                                         stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Residual": [residual], "Out": [out]},
        attrs={"delta": delta},
    )
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hinge_loss",
        inputs={"Logits": [input], "Labels": [label]},
        outputs={"Loss": [out]},
    )
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="rank_loss",
        inputs={"Label": [label], "Left": [left], "Right": [right]},
        outputs={"Out": [out]},
    )
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    act = helper.create_variable_for_type_inference(left.dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Activated": [act], "Out": [out]},
        attrs={"margin": margin},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out
