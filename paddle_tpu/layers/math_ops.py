"""Helpers shared by layers + Variable operator sugar."""

import numpy as np

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper


def to_variable_like(value, ref):
    """Wrap a python scalar/ndarray as a fill_constant/assign_value var."""
    from paddle_tpu.layers import tensor as tensor_layers

    if isinstance(value, framework.Variable):
        return value
    arr = np.asarray(value)
    if arr.ndim == 0:
        return tensor_layers.fill_constant(
            shape=[1], dtype=ref.dtype, value=float(arr)
        )
    return tensor_layers.assign_numpy(arr.astype(ref.dtype))


def elementwise_binary(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, act=act, name=name)
    y = to_variable_like(y, x)
    x = to_variable_like(x, y)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out)


def elementwise_binary_reversed(op_type, var, other, axis=-1):
    """other <op> var, for __rsub__/__rtruediv__/__rpow__."""
    other = to_variable_like(other, var)
    return elementwise_binary(op_type, other, var, axis=axis)
