"""Control-flow layers (python/paddle/fluid/layers/control_flow.py parity,
1987 LoC in the reference).

Comparison layers, increment, tensor arrays, and the sub-block constructs:
``StaticRNN`` (recurrent_op.cc capability -> lax.scan), ``While``
(while_op.cc -> lax.while_loop, forward-only), ``cond``/``IfElse``/``Switch``
(conditional_block_op.cc -> lax.cond), ``DynamicRNN`` (padded-sequence scan
with length masks — the dense-shape replacement for LoD + lod_rank_table
batching, SURVEY.md §5.7).
"""

import contextlib

from paddle_tpu import framework, unique_name
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "increment",
    "is_empty",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "StaticRNN",
    "DynamicRNN",
    "While",
    "Switch",
    "IfElse",
    "cond",
    "lod_rank_table",
    "reorder_lod_tensor_by_rank",
    "lod_tensor_to_array",
    "array_to_lod_tensor",
]


def _compare(op_type):
    def fn(x, y, cond=None, **kwargs):
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [cond]},
        )
        return cond

    fn.__name__ = op_type
    return fn


less_than = _compare("less_than")
less_equal = _compare("less_equal")
greater_than = _compare("greater_than")
greater_equal = _compare("greater_equal")
equal = _compare("equal")
not_equal = _compare("not_equal")


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool",
                                                         stop_gradient=True)
    helper.append_op(type="is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


# -- LoDTensorArray (device repr: (buffer[capacity, ...], size) pair) -------


class LoDTensorArray(list):
    """Host-side tensor array (fluid.LoDTensorArray parity): a plain list
    of arrays/LoDTensors. On device the array ops use a fixed-capacity
    (buffer, size) pair — this class is the feed/fetch-side container."""


def create_array(dtype):
    from paddle_tpu.core.types import VarType

    helper = LayerHelper("array")
    return helper.block.create_var(
        name=unique_name.generate("array"),
        type=VarType.LOD_TENSOR_ARRAY,
        dtype=dtype,
        shape=None,
    )


def array_write(x, i, array=None, capacity=128):
    """Write x into array[i]. First write allocates a static ``capacity``
    buffer (XLA fixed-shape constraint; the reference grows a vector of
    tensors, tensor_array_read_write_op.cc)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x], "I": [i], "Array": [array]}
        if getattr(array, "_array_written", False)
        else {"X": [x], "I": [i]},
        outputs={"Out": [array]},
        attrs={"capacity": int(capacity)},
    )
    array._array_written = True
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


# ---------------------------------------------------------------------------
# Sub-block capture helpers
# ---------------------------------------------------------------------------


def _captured_names(sub_block, local_names):
    """Input names referenced by sub-block ops but not produced locally."""
    produced = set(local_names)
    captured = []
    seen = set(produced)
    for op in sub_block.ops:
        for name in op.input_arg_names():
            if name and name not in seen:
                seen.add(name)
                captured.append(name)
        for name in op.output_arg_names():
            if name:
                produced.add(name)
                seen.add(name)
    return [n for n in captured if n not in set(local_names)]


# ---------------------------------------------------------------------------
# StaticRNN — recurrent op over lax.scan
# ---------------------------------------------------------------------------


class StaticRNN(object):
    """Static (fixed-length) RNN built from a user-defined step block.

    Usage (reference-compatible, layers/control_flow.py StaticRNN):

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)            # x: [batch, T, d]
            h_prev = rnn.memory(shape=[-1, D], batch_ref=x)
            h = layers.fc(input=[x_t, h_prev], size=D, act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                             # [batch, T, D]
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN
        self.seq_inputs = []  # (outer, inner)
        self.memories = []  # (boot outer, pre inner, updated inner or None)
        self.step_outputs = []  # (inner, outer)
        self.sub_block = None
        self._main = self.helper.main_program

    @contextlib.contextmanager
    def step(self):
        if self.status != StaticRNN.BEFORE_RNN:
            raise ValueError("step() can only be entered once")
        self.parent_block = self._main.current_block()
        self.sub_block = self._main.create_block()
        self.status = StaticRNN.IN_RNN
        try:
            yield
        except BaseException:
            # Don't mask the user's error with a completion error.
            self._main.rollback()
            raise
        self._main.rollback()
        self.status = StaticRNN.AFTER_RNN
        self._complete_op()

    def _assert_in_rnn(self):
        if self.status != StaticRNN.IN_RNN:
            raise ValueError("must be called inside `with rnn.step():`")

    def step_input(self, x):
        self._assert_in_rnn()
        shape = None
        if x.shape is not None and len(x.shape) >= 2:
            shape = [x.shape[0]] + list(x.shape[2:])
        inner = self.sub_block.create_var(
            name=unique_name.generate("rnn_step_in"),
            dtype=x.dtype,
            shape=shape,
        )
        self.seq_inputs.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=0):
        self._assert_in_rnn()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "memory() needs either init or (shape and batch_ref)"
                )
            from paddle_tpu.layers import tensor as tensor_layers

            cur = self._main.current_block_idx
            self._main.current_block_idx = self.parent_block.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=batch_ref,
                    shape=list(shape),
                    dtype=batch_ref.dtype,
                    value=init_value,
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=init_batch_dim_idx,
                )
            finally:
                self._main.current_block_idx = cur
        pre = self.sub_block.create_var(
            name=unique_name.generate("rnn_mem"),
            dtype=init.dtype,
            shape=init.shape,
        )
        self.memories.append([init, pre, None])
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn()
        for entry in self.memories:
            if entry[1] is mem or entry[1].name == getattr(mem, "name", mem):
                entry[2] = var
                return
        raise ValueError("update_memory: %s is not a memory of this RNN"
                         % mem.name)

    def step_output(self, o):
        self._assert_in_rnn()
        outer = self.parent_block.create_var(
            name=unique_name.generate("rnn_out"),
            dtype=o.dtype,
            shape=None,
        )
        self.step_outputs.append((o, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete_op(self):
        for boot, pre, updated in self.memories:
            if updated is None:
                raise ValueError(
                    "memory %s was never update_memory()'d" % pre.name
                )
        local = (
            [inner.name for _, inner in self.seq_inputs]
            + [m[1].name for m in self.memories]
        )
        params = _captured_names(self.sub_block, local)
        final_outs = [
            self.parent_block.create_var(
                name=unique_name.generate("rnn_final"),
                dtype=m[0].dtype,
                shape=None,
            )
            for m in self.memories
        ]
        self.parent_block.append_op(
            type="recurrent",
            inputs={
                "inputs": [x.name for x, _ in self.seq_inputs],
                "initial_states": [m[0].name for m in self.memories],
                "parameters": params,
            },
            outputs={
                "outputs": [outer.name for _, outer in self.step_outputs],
                "final_states": [v.name for v in final_outs],
            },
            attrs={
                "sub_block": self.sub_block.idx,
                "input_step_names": [i.name for _, i in self.seq_inputs],
                "pre_state_names": [m[1].name for m in self.memories],
                "state_names": [m[2].name for m in self.memories],
                "output_step_names": [o.name for o, _ in self.step_outputs],
                "param_names": params,
            },
        )
        self.final_states = final_outs

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN:
            raise ValueError("RNN output requested before step block closed")
        outs = [outer for _, outer in self.step_outputs]
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# DynamicRNN — same scan engine, plus length masking sugar
# ---------------------------------------------------------------------------


class DynamicRNN(object):
    """Variable-length RNN over padded [batch, T, d] + lengths.

    The reference's DynamicRNN sorts sequences with lod_rank_table and
    shrinks the batch per step (control_flow.py DynamicRNN); under XLA's
    static shapes the idiomatic equivalent is a full-batch scan with a
    validity mask: memories hold their previous value past each sequence's
    end, and step outputs are zeroed there.
    """

    def __init__(self, lengths=None, name=None):
        self._rnn = StaticRNN(name=name)
        self.lengths = lengths
        self._mask = None
        self._step_idx = None

    @contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            yield

    def step_input(self, x, level=0):
        inner = self._rnn.step_input(x)
        if self.lengths is not None and self._mask is None:
            from paddle_tpu.layers import sequence as seq_layers

            maxlen = int(x.shape[1]) if x.shape and x.shape[1] else None
            if maxlen is None:
                raise ValueError("DynamicRNN needs a static max length")
            # [batch, T] mask computed once in the parent block, scanned.
            main = self._rnn._main
            cur = main.current_block_idx
            main.current_block_idx = self._rnn.parent_block.idx
            try:
                mask = seq_layers.sequence_mask(
                    self.lengths, maxlen=maxlen, dtype="float32"
                )
            finally:
                main.current_block_idx = cur
            self._mask = self._rnn.step_input(mask)
        return inner

    def static_input(self, x):
        # Captured automatically as a parameter of the scan.
        return x

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=0):
        return self._rnn.memory(
            init=init,
            shape=shape,
            batch_ref=batch_ref,
            init_value=init_value,
            init_batch_dim_idx=init_batch_dim_idx,
            ref_batch_dim_idx=ref_batch_dim_idx,
        )

    def update_memory(self, mem, var):
        if self._mask is not None:
            var = _masked_update(var, mem, self._mask)
        self._rnn.update_memory(mem, var)

    def output(self, *outputs):
        outs = []
        for o in outputs:
            if self._mask is not None:
                o = _masked_update(o, None, self._mask)
            outs.append(o)
        self._rnn.output(*outs)

    def __call__(self):
        return self._rnn()


def _masked_update(new, old, mask):
    """new*m + old*(1-m), broadcasting the [batch] step mask."""
    from paddle_tpu.layers import math_ops as ml
    from paddle_tpu.layers import nn as nn_layers

    helper = LayerHelper("masked_update")
    m = nn_layers.unsqueeze(mask, axes=[1]) if len(mask.shape or ()) == 1 \
        else mask
    kept = helper.create_variable_for_type_inference(new.dtype)
    if old is None:
        helper.append_op(
            type="elementwise_mul",
            inputs={"X": [new], "Y": [m]},
            outputs={"Out": [kept]},
            attrs={"axis": 0},
        )
        return kept
    # new*m + old*(1-m) == old + (new-old)*m
    diff = helper.create_variable_for_type_inference(new.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [new], "Y": [old]},
        outputs={"Out": [diff]},
    )
    scaled = helper.create_variable_for_type_inference(new.dtype)
    helper.append_op(
        type="elementwise_mul",
        inputs={"X": [diff], "Y": [m]},
        outputs={"Out": [scaled]},
        attrs={"axis": 0},
    )
    out = helper.create_variable_for_type_inference(new.dtype)
    helper.append_op(
        type="elementwise_add",
        inputs={"X": [old], "Y": [scaled]},
        outputs={"Out": [out]},
    )
    return out


# ---------------------------------------------------------------------------
# While — lax.while_loop (forward-only)
# ---------------------------------------------------------------------------


class While(object):
    """``with While(cond).block():`` loop. Carried vars = every parent-block
    var the body writes; Condition must be a [1] bool var updated in the
    body. Forward-only (decode loops); training recurrences use StaticRNN.
    Reference: while_op.cc:36.
    """

    def __init__(self, cond, max_iterations=0, name=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype not in ("bool",):
            raise TypeError("While condition must be a bool variable")
        self.cond_var = cond
        self.max_iterations = max_iterations
        self._main = self.helper.main_program

    @contextlib.contextmanager
    def block(self):
        parent_block = self._main.current_block()
        sub_block = self._main.create_block()
        try:
            yield
        except BaseException:
            self._main.rollback()
            raise
        else:
            self._main.rollback()
            # Carried vars: sub-block outputs that refer to parent vars
            # (in-place updates), plus the condition var.
            written = []
            seen = set()
            for op in sub_block.ops:
                for name in op.output_arg_names():
                    if (
                        name
                        and name not in seen
                        and parent_block._find_var_recursive(name) is not None
                    ):
                        seen.add(name)
                        written.append(name)
            if self.cond_var.name not in seen:
                raise ValueError(
                    "While body must update the condition variable %s"
                    % self.cond_var.name
                )
            carry = written
            # Fail fast on carried vars with no pre-loop value: every var
            # the body updates must be produced before the loop (tensor
            # arrays included — seed them with an array_write outside).
            for n in carry:
                v = parent_block._find_var_recursive(n)
                if v is not None and v.op is None and not v.is_data \
                        and not v.persistable:
                    raise ValueError(
                        "While carries %r but it has no value before the "
                        "loop; initialize it (fill_constant / array_write) "
                        "before entering While" % n
                    )
            params = [
                n
                for n in _captured_names(sub_block, carry)
                if n not in set(carry)
            ]
            # InitX saves the pre-loop carry values under fresh names so
            # while_grad can restart the loop (Out aliases X in-place).
            from paddle_tpu import unique_name

            init_names = []
            for n in carry:
                v = parent_block._find_var_recursive(n)
                iname = unique_name.generate(n + "__while_init")
                parent_block.create_var(
                    name=iname,
                    shape=None if v is None else v.shape,
                    dtype="float32" if v is None else v.dtype,
                    stop_gradient=True,
                )
                init_names.append(iname)
            parent_block.append_op(
                type="while",
                inputs={"X": carry, "parameters": params},
                outputs={"Out": carry, "InitX": init_names},
                attrs={
                    "sub_block": sub_block.idx,
                    "carry_names": carry,
                    "param_names": params,
                    "cond_name": self.cond_var.name,
                    "max_iterations": int(self.max_iterations),
                },
            )
            # Float carries are (re)defined by the loop body, so gradients
            # must flow through them even though constant initializers
            # (fill_constant & co) mark their outputs stop_gradient —
            # otherwise a loss downstream of the loop never reaches
            # while_grad. A user's explicit stop_gradient on a non-constant
            # carry (detached EMA etc.) is respected.
            from paddle_tpu.core.types import is_float_dtype

            _const_producers = {"fill_constant", "fill_zeros_like",
                                "fill_constant_batch_size_like", "assign_value"}
            for n in carry:
                v = parent_block._find_var_recursive(n)
                if (
                    v is not None
                    and is_float_dtype(v.dtype)
                    and v.op is not None
                    and v.op.type in _const_producers
                ):
                    v.stop_gradient = False


# ---------------------------------------------------------------------------
# cond / IfElse / Switch — lax.cond
# ---------------------------------------------------------------------------


def cond(pred, true_fn, false_fn):
    """Functional two-branch conditional: ``out = cond(p, f, g)``.

    Both branches are traced into sub-blocks and must return the same
    number of variables with matching shapes/dtypes (XLA conditional).
    """
    helper = LayerHelper("cond")
    main = helper.main_program
    parent_block = main.current_block()

    def trace(fn):
        sub = main.create_block()
        try:
            res = fn()
        finally:
            main.rollback()
        if res is None:
            res = []
        if not isinstance(res, (list, tuple)):
            res = [res]
        return sub, list(res)

    sub_t, outs_t = trace(true_fn)
    sub_f, outs_f = trace(false_fn)
    if len(outs_t) != len(outs_f):
        raise ValueError(
            "true_fn returned %d outputs, false_fn %d"
            % (len(outs_t), len(outs_f))
        )
    # Capture sub-block reads AND branch outputs that resolve in the parent
    # block (a branch may pass a parent var through untouched).
    passthrough = [
        v.name
        for v in outs_t + outs_f
        if parent_block._find_var_recursive(v.name) is not None
    ]
    inputs = sorted(
        set(_captured_names(sub_t, []))
        | set(_captured_names(sub_f, []))
        | set(passthrough)
    )
    outs = [
        helper.create_variable_for_type_inference(v.dtype) for v in outs_t
    ]
    parent_block.append_op(
        type="cond",
        inputs={"Cond": [pred.name], "X": inputs},
        outputs={"Out": [o.name for o in outs]},
        attrs={
            "true_block": sub_t.idx,
            "false_block": sub_f.idx,
            "input_names": inputs,
            "true_out_names": [v.name for v in outs_t],
            "false_out_names": [v.name for v in outs_f],
        },
    )
    return outs[0] if len(outs) == 1 else outs


class Switch(object):
    """``with switch.case(cond): ... with switch.default(): ...``

    Reference: layers/control_flow.py Switch (chained conditional_blocks).
    Here each case body must assign to the same output vars via
    layers.assign; cases compile to nested lax.cond.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.cases = []  # (cond_var or None, sub_block)
        self._main = self.helper.main_program
        self.parent_block = self._main.current_block()

    @contextlib.contextmanager
    def case(self, condition):
        sub = self._main.create_block()
        try:
            yield
        finally:
            self._main.rollback()
        self.cases.append((condition, sub))

    @contextlib.contextmanager
    def default(self):
        sub = self._main.create_block()
        try:
            yield
        finally:
            self._main.rollback()
        self.cases.append((None, sub))
        self._complete()

    def _complete(self):
        # Outputs: union of names written by any case that exist in parent.
        out_names = []
        seen = set()
        for _, sub in self.cases:
            for op in sub.ops:
                for n in op.output_arg_names():
                    if (
                        n
                        and n not in seen
                        and self.parent_block._find_var_recursive(n)
                        is not None
                    ):
                        seen.add(n)
                        out_names.append(n)
        default = None
        conds = []
        for c, sub in self.cases:
            if c is None:
                default = sub
            else:
                conds.append((c, sub))
        if default is None:
            raise ValueError("Switch requires a default() case")
        # Build nested conds from the last case inward.
        inputs = sorted(
            set(
                n
                for _, sub in self.cases
                for n in _captured_names(sub, [])
            )
            | set(out_names)
        )

        # Chain of cond ops in the parent block: default first, then each
        # case from last to first, so the FIRST matching case wins. The
        # default link uses a constant-true predicate (XLA folds it).
        current_names = list(out_names)  # fall-through = pre-switch values
        chain = [(None, default)] + list(reversed(conds))
        for c, sub in chain:
            if c is None:
                from paddle_tpu.layers import tensor as tensor_layers

                c = tensor_layers.fill_constant([1], "bool", True)
            new_outs = [
                self.parent_block.create_var(
                    name=unique_name.generate("switch_out"),
                    dtype=self.parent_block._find_var_recursive(n).dtype,
                    shape=None,
                )
                for n in out_names
            ]
            # false branch: identity sub-block (pass-through of current).
            ident = self._main.create_block()
            self._main.rollback()
            self.parent_block.append_op(
                type="cond",
                inputs={"Cond": [c.name], "X": inputs},
                outputs={"Out": [v.name for v in new_outs]},
                attrs={
                    "true_block": sub.idx,
                    "false_block": ident.idx,
                    "input_names": inputs,
                    "true_out_names": out_names,
                    "false_out_names": current_names,
                },
            )
            current_names = [v.name for v in new_outs]
            inputs = sorted(set(inputs) | set(current_names))
        # Bind results back to the original names via assign.
        from paddle_tpu.layers import tensor as tensor_layers

        for orig, cur in zip(out_names, current_names):
            tensor_layers.assign(
                self.parent_block._find_var_recursive(cur),
                self.parent_block._find_var_recursive(orig),
            )


class IfElse(object):
    """Reference layers/control_flow.py IfElse. Batch-element conditional:
    true_block/false_block each transform the full batch; outputs are
    merged elementwise by the [batch, 1] bool condition (select), which is
    the XLA-friendly equivalent of the reference's split/merge ops."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._main = self.helper.main_program
        self.parent_block = self._main.current_block()
        self._true_outs = None
        self._false_outs = None
        self._in_true = False
        self._inputs = []

    @contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        yield
        self._in_true = False

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        yield

    def input(self, x):
        return x

    def output(self, *outs):
        if self._in_true:
            self._true_outs = list(outs)
        else:
            self._false_outs = list(outs)

    def __call__(self):
        if self._true_outs is None or self._false_outs is None:
            raise ValueError("both branches must call output()")
        from paddle_tpu.layers import nn as nn_layers

        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            out = self.helper.create_variable_for_type_inference(t.dtype)
            self.helper.append_op(
                type="where_select",
                inputs={"Cond": [self.cond], "X": [t], "Y": [f]},
                outputs={"Out": [out]},
            )
            merged.append(out)
        return merged


class RankTable(object):
    """Build-time handle to a sequence rank table: sequences sorted by
    descending length (ties stable). ``index``/``length`` are [batch]
    int64 Variables computed at run time — unlike the reference's
    LOD_RANK_TABLE (control_flow.py:741), contents are not inspectable at
    build time because lengths are runtime tensors here."""

    def __init__(self, index, length):
        self.index = index
        self.length = length


def lod_rank_table(x=None, level=0, lengths=None):
    """Rank sequences by descending length (lod_rank_table op role).

    The reference reads the LoD of ``x``; in the dense-padded design
    (docs/LOD_DESIGN.md) lengths are an explicit tensor, so pass
    ``lengths`` ([batch] or [batch, 1] int). ``x`` and ``level`` are
    accepted for API compatibility; ``level`` must be 0 (one ragged
    level on device).
    """
    if lengths is None:
        raise ValueError(
            "lod_rank_table needs lengths= (the dense-padded design "
            "carries sequence lengths as an explicit tensor; see "
            "docs/LOD_DESIGN.md)")
    if level != 0:
        raise ValueError("only level=0 is supported on device")
    helper = LayerHelper("lod_rank_table")
    index = helper.create_variable_for_type_inference("int64")
    sorted_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_rank_table",
        inputs={"Length": [lengths]},
        outputs={"Index": [index], "SortedLength": [sorted_len]},
    )
    return RankTable(index, sorted_len)


def lod_tensor_to_array(x, table):
    """Move a dense-padded [B, T, ...] tensor into a tensor array whose
    time axis is the array index (lod_tensor_to_array_op.cc role; the
    reference splits ragged rows per rank-table bucket, the dense design
    re-axes the padded tensor — docs/LOD_DESIGN.md)."""
    from paddle_tpu.core.types import VarType

    helper = LayerHelper("lod_tensor_to_array")
    array = helper.block.create_var(
        name=unique_name.generate("lod_tensor_to_array"),
        type=VarType.LOD_TENSOR_ARRAY,
        dtype=x.dtype,
        shape=None,
    )
    helper.append_op(
        type="lod_tensor_to_array",
        inputs={"X": [x], "RankTable": [table.index]},
        outputs={"Out": [array]},
    )
    array._array_written = True
    return array


def array_to_lod_tensor(x, table):
    """Inverse of lod_tensor_to_array: stack the array back into a dense
    batch-major [B, T, ...] tensor (array_to_lod_tensor_op.cc role)."""
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="array_to_lod_tensor",
        inputs={"X": [x], "RankTable": [table.index]},
        outputs={"Out": [out]},
    )
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute ``x``'s batch dimension into the rank table's order
    (reorder_lod_tensor_by_rank_op.cc role). Gradient scatters back
    through the permutation."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankIndex": [rank_table.index]},
        outputs={"Out": [out]},
    )
    return out
