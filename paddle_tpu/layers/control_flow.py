"""Control-flow layers (layers/control_flow.py parity — 1987 LoC in ref).

First wave: comparison layers, increment, array ops. While/StaticRNN/
DynamicRNN arrive with the sequence wave (lowered to lax.scan /
lax.while_loop via sub-blocks).
"""

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "increment",
    "is_empty",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
]


def _compare(op_type):
    def fn(x, y, cond=None, **kwargs):
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [cond]},
        )
        return cond

    fn.__name__ = op_type
    return fn


less_than = _compare("less_than")
less_equal = _compare("less_equal")
greater_than = _compare("greater_than")
greater_equal = _compare("greater_equal")
equal = _compare("equal")
not_equal = _compare("not_equal")


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool",
                                                         stop_gradient=True)
    helper.append_op(type="is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


# -- LoDTensorArray facade (host-managed; scan-based RNNs do not need it, it
#    exists for API parity with array_read/array_write user code) -----------


def create_array(dtype):
    from paddle_tpu import unique_name
    from paddle_tpu.core.types import VarType

    helper = LayerHelper("array")
    return helper.block.create_var(
        name=unique_name.generate("array"),
        type=VarType.LOD_TENSOR_ARRAY,
        dtype=dtype,
        shape=None,
    )


def array_write(x, i, array=None):
    raise NotImplementedError(
        "tensor-array ops land with the DynamicRNN/scan wave; use "
        "layers.StaticRNN or the dense sequence layers instead"
    )


def array_read(array, i):
    raise NotImplementedError(
        "tensor-array ops land with the DynamicRNN/scan wave"
    )


def array_length(array):
    raise NotImplementedError(
        "tensor-array ops land with the DynamicRNN/scan wave"
    )
