"""User-facing layers API (python/paddle/fluid/layers parity)."""

from paddle_tpu.layers import math_ops  # noqa: F401
from paddle_tpu.layers.tensor import *  # noqa: F401,F403
from paddle_tpu.layers.ops import *  # noqa: F401,F403
from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.io import *  # noqa: F401,F403
from paddle_tpu.layers.control_flow import *  # noqa: F401,F403
from paddle_tpu.layers.metric_op import *  # noqa: F401,F403
from paddle_tpu.layers.loss import *  # noqa: F401,F403
from paddle_tpu.layers import learning_rate_scheduler  # noqa: F401
from paddle_tpu.layers.learning_rate_scheduler import (  # noqa: F401
    exponential_decay,
    natural_exp_decay,
    inverse_time_decay,
    polynomial_decay,
    piecewise_decay,
    noam_decay,
    cosine_decay,
    append_LARS,
)
from paddle_tpu.layers.sequence import *  # noqa: F401,F403
from paddle_tpu.layers.rnn import *  # noqa: F401,F403
from paddle_tpu.layers.attention import *  # noqa: F401,F403
from paddle_tpu.layers.nlp import *  # noqa: F401,F403
from paddle_tpu.layers.detection import *  # noqa: F401,F403
