"""Detection layers (layers/detection.py parity) — first wave."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["prior_box", "iou_similarity", "box_coder"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    variances = helper.create_variable_for_type_inference(input.dtype,
                                                          stop_gradient=True)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out
