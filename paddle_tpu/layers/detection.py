"""Detection layers.

Reference parity: python/paddle/fluid/layers/detection.py (prior_box,
multi_box_head, bipartite_match, target_assign, detection_output, ssd_loss,
detection_map, rpn_target_assign, anchor_generator, generate_proposals,
iou_similarity, box_coder, polygon_box_transform) plus roi_pool/roi_align
(reference keeps those in layers/nn.py; grouped here with the rest of the
detection surface).

TPU-first conventions (vs the reference's LoD ground truth):
  * ground-truth boxes are a padded dense batch ``[N, G, 4]`` where padded
    rows are all-zero; labels ``[N, G]`` use -1 (or any value — zero-box rows
    are ignored by the matcher);
  * index-list outputs (NegIndices) become dense masks;
  * NMS-style ops emit fixed-capacity results padded with label -1 plus an
    explicit per-image count.
"""

import math

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import nn, tensor
from paddle_tpu.layers import loss as loss_layers

__all__ = [
    "prior_box",
    "density_prior_box",
    "multi_box_head",
    "bipartite_match",
    "target_assign",
    "detection_output",
    "multiclass_nms",
    "ssd_loss",
    "detection_map",
    "rpn_target_assign",
    "anchor_generator",
    "generate_proposals",
    "generate_proposal_labels",
    "roi_perspective_transform",
    "iou_similarity",
    "box_coder",
    "polygon_box_transform",
    "roi_pool",
    "roi_align",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    variances = helper.create_variable_for_type_inference(input.dtype,
                                                          stop_gradient=True)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios=(1.0,),
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype,
                                                      stop_gradient=True)
    variances = helper.create_variable_for_type_inference(input.dtype,
                                                          stop_gradient=True)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "densities": list(densities),
            "fixed_sizes": list(fixed_sizes),
            "fixed_ratios": list(fixed_ratios),
            "variances": list(variance),
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "flatten_to_2d": flatten_to_2d,
        },
    )
    if flatten_to_2d:
        boxes = nn.reshape(boxes, shape=[-1, 4])
        variances = nn.reshape(variances, shape=[-1, 4])
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching on a padded distance matrix [N, G, P].

    Returns (match_indices [N, P] int32 with -1 for unmatched, match_dist
    [N, P]). Reference: bipartite_match_op.cc.
    """
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    match_dist = helper.create_variable_for_type_inference(
        dist_matrix.dtype, stop_gradient=True)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={
            "ColToRowMatchIndices": [match_indices],
            "ColToRowMatchDist": [match_dist],
        },
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return match_indices, match_dist


def target_assign(input, match_indices, negative_mask=None, mismatch_value=0,
                  name=None):
    """Assign per-prior targets by match index; returns (out, out_weight).

    ``input`` is [N, G, K] (per-gt rows) or [N, G, P, K] (per-gt-per-prior,
    e.g. encoded boxes). ``negative_mask`` [N, P] marks hard negatives whose
    weight is forced to 1 (the reference's NegIndices LoD, densified).
    Reference: target_assign_op.cc.
    """
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    out_weight = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    inputs = {"X": [input], "MatchIndices": [match_indices]}
    if negative_mask is not None:
        inputs["NegMask"] = [negative_mask]
    helper.append_op(
        type="target_assign",
        inputs=inputs,
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, out_weight


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.0,
                   nms_top_k=-1, nms_threshold=0.3, nms_eta=1.0,
                   keep_top_k=-1, normalized=True, name=None):
    """Multi-class NMS. scores [N, C, P], bboxes [N, P, 4].

    Returns (out [N, keep_top_k, 6] padded with label -1, count [N]).
    Reference: multiclass_nms_op.cc (LoD output becomes padded + count).
    """
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype,
                                                    stop_gradient=True)
    count = helper.create_variable_for_type_inference("int32",
                                                      stop_gradient=True)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "Count": [count]},
        attrs={
            "background_label": background_label,
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "nms_threshold": nms_threshold,
            "nms_eta": nms_eta,
            "keep_top_k": keep_top_k,
            "normalized": normalized,
        },
    )
    return out, count


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """SSD inference head: decode loc against priors, softmax scores, NMS.

    loc [N, P, 4], scores [N, P, C]. Returns the padded NMS output
    [N, keep_top_k, 6]. Reference: layers/detection.py:197 detection_output.
    """
    decoded = box_coder(
        prior_box=prior_box,
        prior_box_var=prior_box_var,
        target_box=loc,
        code_type="decode_center_size",
    )
    probs = nn.softmax(scores)
    probs = nn.transpose(probs, perm=[0, 2, 1])  # [N, C, P]
    out, _ = multiclass_nms(
        bboxes=decoded,
        scores=probs,
        background_label=background_label,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        nms_threshold=nms_threshold,
        nms_eta=nms_eta,
        keep_top_k=keep_top_k,
        name=name,
    )
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (match -> mine hard negatives -> assign -> loss).

    location [N, P, 4], confidence [N, P, C], gt_box [N, G, 4] zero-padded,
    gt_label [N, G] (or [N, G, 1]) int. Returns loss [N, 1].
    Reference: layers/detection.py:672 ssd_loss (same five steps, dense).
    """
    if mining_type != "max_negative":
        raise ValueError("Only mining_type == max_negative is supported.")
    helper = LayerHelper("ssd_loss")
    num, num_prior, num_class = confidence.shape

    # 1. match priors to ground truth
    iou = iou_similarity(x=gt_box, y=prior_box)  # [N, G, P]
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold)

    # 2. confidence loss against matched labels (for mining)
    if len(gt_label.shape) == 2:
        gt_label3 = nn.reshape(gt_label, shape=[0, -1, 1])
    else:
        gt_label3 = gt_label
    target_label, _ = target_assign(
        gt_label3, matched_indices, mismatch_value=background_label)
    conf2d = nn.reshape(confidence, shape=[-1, num_class])
    tl2d = nn.reshape(tensor.cast(target_label, "int32"), shape=[-1, 1])
    tl2d.stop_gradient = True
    conf_loss = loss_layers.softmax_with_cross_entropy(conf2d, tl2d)
    conf_loss = nn.reshape(conf_loss, shape=[num, num_prior])
    conf_loss.stop_gradient = True

    # 3. mine hard negatives
    neg_mask = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    updated_indices = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    helper.append_op(
        type="mine_hard_examples",
        inputs={
            "ClsLoss": [conf_loss],
            "MatchIndices": [matched_indices],
            "MatchDist": [matched_dist],
        },
        outputs={
            "NegMask": [neg_mask],
            "UpdatedMatchIndices": [updated_indices],
        },
        attrs={
            "neg_pos_ratio": neg_pos_ratio,
            "neg_dist_threshold": neg_overlap,
            "mining_type": mining_type,
            "sample_size": sample_size or 0,
        },
    )

    # 4. regression + classification targets
    encoded_bbox = box_coder(
        prior_box=prior_box,
        prior_box_var=prior_box_var,
        target_box=gt_box,
        code_type="encode_center_size",
    )  # [N, G, P, 4]
    target_bbox, target_loc_weight = target_assign(
        encoded_bbox, updated_indices, mismatch_value=background_label)
    target_label, target_conf_weight = target_assign(
        gt_label3, updated_indices, negative_mask=neg_mask,
        mismatch_value=background_label)

    # 5. weighted losses
    tl2d = nn.reshape(tensor.cast(target_label, "int32"), shape=[-1, 1])
    tl2d.stop_gradient = True
    conf_loss = loss_layers.softmax_with_cross_entropy(conf2d, tl2d)
    tcw2d = nn.reshape(target_conf_weight, shape=[-1, 1])
    tcw2d.stop_gradient = True
    conf_loss = nn.elementwise_mul(conf_loss, tcw2d)

    loc2d = nn.reshape(location, shape=[-1, 4])
    tb2d = nn.reshape(target_bbox, shape=[-1, 4])
    tb2d.stop_gradient = True
    loc_loss = loss_layers.smooth_l1(loc2d, tb2d)
    tlw2d = nn.reshape(target_loc_weight, shape=[-1, 1])
    tlw2d.stop_gradient = True
    loc_loss = nn.elementwise_mul(loc_loss, tlw2d)

    loss = nn.elementwise_add(
        nn.scale(conf_loss, scale=conf_loss_weight),
        nn.scale(loc_loss, scale=loc_loss_weight),
    )
    loss = nn.reshape(loss, shape=[-1, num_prior])
    loss = nn.reduce_sum(loss, dim=1, keep_dim=True)
    if normalize:
        normalizer = nn.reduce_sum(tlw2d)
        normalizer.stop_gradient = True
        loss = nn.elementwise_div(loss, normalizer)
    return loss


def detection_map(detect_res, gt_label, gt_box, gt_difficult=None,
                  class_num=None, background_label=0, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_version="integral", name=None):
    """mAP over padded detections [N, D, 6] and dense ground truth.

    Reference: detection_map_op.cc; accumulative multi-batch mAP lives in
    paddle_tpu.metrics.DetectionMAP (host-side), this op scores one batch
    in-graph.
    """
    if class_num is None:
        raise ValueError("detection_map requires class_num")
    helper = LayerHelper("detection_map", name=name)
    m_ap = helper.create_variable_for_type_inference("float32",
                                                     stop_gradient=True)
    inputs = {
        "DetectRes": [detect_res],
        "GtLabel": [gt_label],
        "GtBox": [gt_box],
    }
    if gt_difficult is not None:
        inputs["GtDifficult"] = [gt_difficult]
    helper.append_op(
        type="detection_map",
        inputs=inputs,
        outputs={"MAP": [m_ap]},
        attrs={
            "overlap_threshold": overlap_threshold,
            "evaluate_difficult": evaluate_difficult,
            "ap_type": ap_version,
            "class_num": class_num,
            "background_label": background_label,
        },
    )
    return m_ap


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True)
    variances = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True)
    helper.append_op(
        type="anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={
            "anchor_sizes": list(anchor_sizes or [64.0, 128.0, 256.0, 512.0]),
            "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
            "variances": list(variance),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        },
    )
    return anchors, variances


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var, gt_boxes,
                      is_crowd=None, im_info=None, rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      use_random=True):
    """RPN anchor sampling; fixed-size index outputs padded with -1.

    bbox_pred [N, A, 4], cls_logits [N, A, 1], anchor_box [A, 4],
    gt_boxes [N, G, 4] zero-padded, im_info [N, 3]. Returns
    (predicted_cls_logits [N, S_fg+S, 1], predicted_bbox_pred [N, S_fg, 4],
    target_label [N, S_fg+S], target_bbox [N, S_fg, 4],
    bbox_inside_weight [N, S_fg, 4], label_weight [N, S_fg+S]) where
    S = rpn_batch_size_per_im, S_fg = round(S * fg_fraction). Slots are
    fixed capacity (fg slots first, then up to S - num_fg negatives);
    label_weight marks the valid samples — exactly S of them when enough
    candidates exist, fewer only when the image lacks candidates.
    Reference: rpn_target_assign_op.cc:490-560 + layers/detection.py:51.
    """
    helper = LayerHelper("rpn_target_assign")
    dt = anchor_box.dtype
    mk = lambda d: helper.create_variable_for_type_inference(
        d, stop_gradient=True)
    loc_index, score_index = mk("int32"), mk("int32")
    target_bbox, target_label = mk(dt), mk("int32")
    bbox_inside_weight, label_weight = mk("float32"), mk("float32")
    inputs = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd]
    if im_info is not None:
        inputs["ImInfo"] = [im_info]
    helper.append_op(
        type="rpn_target_assign",
        inputs=inputs,
        outputs={
            "LocIndex": [loc_index],
            "ScoreIndex": [score_index],
            "TargetBBox": [target_bbox],
            "TargetLabel": [target_label],
            "BBoxInsideWeight": [bbox_inside_weight],
            "LabelWeight": [label_weight],
        },
        attrs={
            "rpn_batch_size_per_im": rpn_batch_size_per_im,
            "rpn_straddle_thresh": rpn_straddle_thresh,
            "rpn_fg_fraction": rpn_fg_fraction,
            "rpn_positive_overlap": rpn_positive_overlap,
            "rpn_negative_overlap": rpn_negative_overlap,
            "use_random": use_random,
        },
    )
    # gather predictions at the sampled indices (-1 padding clamps to row 0
    # inside batched_gather; mask with the weight outputs)
    predicted_cls_logits = nn.batched_gather(cls_logits, score_index)
    predicted_bbox_pred = nn.batched_gather(bbox_pred, loc_index)
    return (predicted_cls_logits, predicted_bbox_pred, target_label,
            target_bbox, bbox_inside_weight, label_weight)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation; fixed-capacity rois + per-image count.

    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors [H, W, A, 4].
    Returns (rpn_rois [N, post_nms_top_n, 4], rpn_roi_probs, rois_count [N]).
    Reference: generate_proposals_op.cc.
    """
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(
        scores.dtype, stop_gradient=True)
    probs = helper.create_variable_for_type_inference(
        scores.dtype, stop_gradient=True)
    count = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    helper.append_op(
        type="generate_proposals",
        inputs={
            "Scores": [scores],
            "BboxDeltas": [bbox_deltas],
            "ImInfo": [im_info],
            "Anchors": [anchors],
            "Variances": [variances],
        },
        outputs={
            "RpnRois": [rois],
            "RpnRoiProbs": [probs],
            "RpnRoisCount": [count],
        },
        attrs={
            "pre_nms_topN": pre_nms_top_n,
            "post_nms_topN": post_nms_top_n,
            "nms_thresh": nms_thresh,
            "min_size": min_size,
            "eta": eta,
        },
    )
    return rois, probs, count


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="polygon_box_transform",
        inputs={"Input": [input]},
        outputs={"Output": [out]},
    )
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch=None, name=None):
    """Quantized max pooling over ROIs. rois [R, 4]; rois_batch [R] maps each
    roi to its image (the reference's ROI-LoD, densified).
    Reference: roi_pool_op.cc."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        type="roi_pool",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, rois_batch=None, name=None):
    """Bilinear average pooling over ROIs. Reference: roi_align_op.cc."""
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        type="roi_align",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
            "sampling_ratio": sampling_ratio,
        },
    )
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multibox head: per-feature-map loc/conf convs + prior boxes.

    Returns (mbox_loc [N, total_priors, 4], mbox_conf [N, total_priors, C],
    boxes [total_priors, 4], variances [total_priors, 4]).
    Reference: layers/detection.py:1026 multi_box_head.
    """
    n_layer = len(inputs)
    if min_sizes is None:
        # derive sizes from the ratio range, as the SSD paper does
        assert n_layer > 2 and min_ratio is not None and max_ratio is not None
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes

    def _per_layer(v, i, default):
        if v is None:
            return default
        return v[i] if isinstance(v, (list, tuple)) else v

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = _per_layer(aspect_ratios, i, [1.0])
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        sw = _per_layer(step_w, i, _per_layer(steps, i, 0.0))
        sh = _per_layer(step_h, i, _per_layer(steps, i, 0.0))
        box, var = prior_box(
            feat, image, [ms] if not isinstance(ms, (list, tuple)) else ms,
            [mx] if mx is not None else None, ar, variance, flip, clip,
            steps=(sw or 0.0, sh or 0.0), offset=offset)
        box2 = nn.reshape(box, shape=[-1, 4])
        var2 = nn.reshape(var, shape=[-1, 4])
        boxes_all.append(box2)
        vars_all.append(var2)
        num_priors = int(box2.shape[0]) // (
            int(feat.shape[2]) * int(feat.shape[3]))

        loc = nn.conv2d(feat, num_filters=num_priors * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        locs.append(nn.reshape(loc, shape=[0, -1, 4]))

        conf = nn.conv2d(feat, num_filters=num_priors * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        confs.append(nn.reshape(conf, shape=[0, -1, num_classes]))

    mbox_loc = tensor.concat(locs, axis=1)
    mbox_conf = tensor.concat(confs, axis=1)
    boxes = tensor.concat(boxes_all, axis=0)
    variances = tensor.concat(vars_all, axis=0)
    return mbox_loc, mbox_conf, boxes, variances


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """Fast R-CNN RoI sampling (generate_proposal_labels_op.cc). Fixed
    capacity: S_fg + S slots per image (fg first), labels -1 on padding,
    with a RoisWeight mask marking valid samples. Returns (rois, labels,
    bbox_targets, bbox_inside_weights, bbox_outside_weights, rois_weight).
    """
    if class_nums is None:
        raise ValueError("generate_proposal_labels requires class_nums")
    helper = LayerHelper("generate_proposal_labels")
    mk = lambda d: helper.create_variable_for_type_inference(
        d, stop_gradient=True)
    rois = mk(rpn_rois.dtype)
    labels = mk("int32")
    targets, inw, outw, rw = mk("float32"), mk("float32"), mk("float32"), \
        mk("float32")
    inputs = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
              "GtBoxes": [gt_boxes]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd]
    if im_info is not None:
        inputs["ImInfo"] = [im_info]
    helper.append_op(
        type="generate_proposal_labels",
        inputs=inputs,
        outputs={
            "Rois": [rois], "LabelsInt32": [labels],
            "BboxTargets": [targets], "BboxInsideWeights": [inw],
            "BboxOutsideWeights": [outw], "RoisWeight": [rw],
        },
        attrs={
            "batch_size_per_im": batch_size_per_im,
            "fg_fraction": fg_fraction,
            "fg_thresh": fg_thresh,
            "bg_thresh_hi": bg_thresh_hi,
            "bg_thresh_lo": bg_thresh_lo,
            "bbox_reg_weights": list(bbox_reg_weights),
            "class_nums": class_nums,
            "use_random": use_random,
        },
    )
    return rois, labels, targets, inw, outw, rw


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch=None, name=None):
    """Perspective-warp quadrilateral ROIs [R, 8] to a fixed rectangle
    (roi_perspective_transform_op.cc, EAST-style text recognition)."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        type="roi_perspective_transform",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "transformed_height": transformed_height,
            "transformed_width": transformed_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out
