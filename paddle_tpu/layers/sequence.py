"""Sequence layers over the dense [batch, max_len, ...] + length repr.

Reference parity: the sequence_* layer family in layers/nn.py (LoD-based in
the reference; masked-dense on TPU, per SURVEY.md §5.7).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "sequence_pool",
    "sequence_softmax",
    "sequence_reverse",
    "sequence_mask",
    "sequence_first_step",
    "sequence_last_step",
]


def _seq_op(op_type, x, length, out_slot, attrs=None, extra_outputs=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if length is not None:
        inputs["Length"] = [length]
    outputs = {out_slot: [out]}
    for slot in extra_outputs or []:
        outputs[slot] = [
            helper.create_variable_for_type_inference("int32", stop_gradient=True)
        ]
    helper.append_op(type=op_type, inputs=inputs, outputs=outputs, attrs=attrs or {})
    return out


def sequence_pool(input, pool_type, length=None):
    return _seq_op(
        "sequence_pool",
        input,
        length,
        "Out",
        attrs={"pooltype": pool_type.upper()},
        extra_outputs=["MaxIndex"],
    )


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    return _seq_op("sequence_softmax", input, length, "Out")


def sequence_reverse(x, length=None, name=None):
    return _seq_op("sequence_reverse", x, length, "Y")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen if maxlen is not None else -1, "out_dtype": dtype},
    )
    return out
