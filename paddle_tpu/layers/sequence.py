"""Sequence layers over the dense [batch, max_len, ...] + length repr.

Reference parity: the sequence_* layer family in layers/nn.py (LoD-based in
the reference; masked-dense on TPU, per SURVEY.md §5.7).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "sequence_reshape",
    "sequence_pool",
    "sequence_softmax",
    "sequence_reverse",
    "sequence_mask",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_conv",
    "sequence_concat",
    "sequence_expand_as",
    "sequence_pad",
    "sequence_unpad",
    "sequence_slice",
    "sequence_erase",
    "sequence_enumerate",
    "sequence_scatter",
]


def _seq_op(op_type, x, length, out_slot, attrs=None, extra_outputs=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if length is not None:
        inputs["Length"] = [length]
    outputs = {out_slot: [out]}
    for slot in extra_outputs or []:
        outputs[slot] = [
            helper.create_variable_for_type_inference("int32", stop_gradient=True)
        ]
    helper.append_op(type=op_type, inputs=inputs, outputs=outputs, attrs=attrs or {})
    return out


def sequence_pool(input, pool_type, length=None):
    return _seq_op(
        "sequence_pool",
        input,
        length,
        "Out",
        attrs={"pooltype": pool_type.upper()},
        extra_outputs=["MaxIndex"],
    )


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    return _seq_op("sequence_softmax", input, length, "Out")


def sequence_reverse(x, length=None, name=None):
    return _seq_op("sequence_reverse", x, length, "Y")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen if maxlen is not None else -1, "out_dtype": dtype},
    )
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, length=None, bias_attr=None, param_attr=None,
                  act=None):
    """Context-window convolution over time (sequence_conv_op.cc)."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = input.dtype
    filter_shape = [filter_size * int(input.shape[-1]), num_filters]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "Filter": [filter_param]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_conv",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "contextLength": filter_size,
            "contextStart": -int(filter_size // 2),
            "contextStride": filter_stride,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_concat(input, lengths=None, name=None):
    """Concatenate valid prefixes along time (sequence_concat_op.cc)."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    out_len = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True
    )
    inputs = {"X": list(input)}
    if lengths is not None:
        inputs["Length"] = list(lengths)
    helper.append_op(
        type="sequence_concat",
        inputs=inputs,
        outputs={"Out": [out], "OutLength": [out_len]},
    )
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand_as",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    inputs = {"X": [x], "PadValue": [pad_value]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_pad",
        inputs=inputs,
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"padded_length": maxlen if maxlen is not None else -1},
    )
    return out, out_len


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_unpad",
        inputs={"X": [x], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_erase(input, tokens, length=None, name=None):
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True
    )
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_erase",
        inputs=inputs,
        outputs={"Out": [out], "OutLength": [out_len]},
        attrs={"tokens": list(tokens)},
    )
    return out, out_len


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True
    )
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_enumerate",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"win_size": int(win_size), "pad_value": pad_value},
    )
    return out


def sequence_scatter(input, index, updates, name=None):
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
    )
    return out


def sequence_reshape(input, new_dim):
    """Re-chunk the trailing feature dim (sequence_reshape_op.cc); on the
    padded layout [B, T, D] -> [B, T*D/new_dim, new_dim]."""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"new_dim": new_dim},
    )
    return out
