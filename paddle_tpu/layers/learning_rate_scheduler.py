"""LR schedules as in-graph ops over a global step counter.

Reference parity: python/paddle/fluid/layers/learning_rate_scheduler.py
(noam/exponential/natural_exp/inverse_time/polynomial/piecewise/cosine).
The schedule is computed from a persistable @LR_DECAY_COUNTER@ var
incremented each step — all inside the compiled step program.
"""

import math

from paddle_tpu import framework
from paddle_tpu import initializer as init_mod
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import tensor, ops
from paddle_tpu.layers import nn

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "append_LARS",
]

_DECAY_COUNTER = "@LR_DECAY_COUNTER@"


def _global_step_counter(counter_name=None, begin=0, step=1):
    """Shared per-program step counter. The increment op is appended only
    when the counter var is first created, so several call sites (two LR
    schedules, user autoincreased_step_counter) share ONE +step per run —
    the reference's is-new-var guard. Kept float32 (x64 is off on TPU;
    exact to 2^24 steps) where the reference uses int64."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or _DECAY_COUNTER
    gblock = helper.main_program.global_block()
    existed = gblock.has_var(name)
    counter = helper.create_global_variable(
        name=name, shape=[1], dtype="float32", persistable=True,
        initializer=init_mod.ConstantInitializer(float(begin - step)),
    )
    if not existed:
        gblock.append_op(
            type="increment",
            inputs={"X": [counter.name]},
            outputs={"Out": [counter.name]},
            attrs={"step": float(step), framework.OP_ROLE_ATTR_NAME:
                   framework.OpRole.LRSched},
        )
    return counter


def noam_decay(d_model, warmup_steps):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        a = step ** -0.5
        b = step * (warmup_steps ** -1.5)
        lr = (d_model ** -0.5) * nn.elementwise_min(a, b)
        return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        return learning_rate * (decay_rate ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        return learning_rate * ops.exp(-1.0 * decay_rate * div)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        denom = div * decay_rate + 1.0
        return nn.elementwise_div(
            tensor.fill_constant([1], "float32", learning_rate), denom
        )


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        if cycle:
            div_res = ops.ceil(step / float(decay_steps))
            ones = tensor.fill_constant([1], "float32", 1.0)
            div_res = nn.elementwise_max(div_res, ones)
            decay_steps_var = div_res * float(decay_steps)
            frac = step / decay_steps_var
        else:
            capped = nn.elementwise_min(
                step, tensor.fill_constant([1], "float32", float(decay_steps))
            )
            frac = capped * (1.0 / float(decay_steps))
        # (1 - frac)^power
        base = nn.elementwise_sub(
            tensor.fill_constant([1], "float32", 1.0), frac
        )
        powed = nn.elementwise_pow(
            base, tensor.fill_constant([1], "float32", power)
        )
        return powed * (learning_rate - end_learning_rate) + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise constant: computed with nested where via compare ops."""
    assert len(boundaries) + 1 == len(values)
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        lr = tensor.fill_constant([1], "float32", values[-1])
        # Build from the last interval backwards: where(step < b_i, v_i, lr)
        for b, v in zip(reversed(boundaries), reversed(values[:-1])):
            from paddle_tpu.layers.control_flow import less_than

            cond = less_than(step, tensor.fill_constant([1], "float32", float(b)))
            v_var = tensor.fill_constant([1], "float32", v)
            lr = _where(cond, v_var, lr)
        return lr


def _where(cond, a, b):
    from paddle_tpu.layers.nn import elementwise_add, elementwise_mul, elementwise_sub

    cond_f = tensor.cast(cond, a.dtype)
    one = tensor.fill_constant([1], a.dtype, 1.0)
    return elementwise_add(
        elementwise_mul(a, cond_f), elementwise_mul(b, elementwise_sub(one, cond_f))
    )


def cosine_decay(learning_rate, step_each_epoch, epochs):
    with framework.default_main_program()._lr_schedule_guard():
        step = _global_step_counter()
        epoch = ops.floor(step / float(step_each_epoch))
        return (
            learning_rate
            * (ops.cos(epoch * (math.pi / float(epochs))) + 1.0)
            / 2.0
        )


def append_LARS(params_grads, learning_rate, weight_decay):
    raise NotImplementedError(
        "use optimizer.LarsMomentumOptimizer (lars_momentum op) instead"
    )
