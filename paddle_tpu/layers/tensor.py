"""Tensor creation / manipulation layers (layers/tensor.py parity)."""

import numpy as np

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "pad_constant_like",
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "assign_numpy",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "zeros_like",
    "reverse",
    "argmax",
    "argmin",
    "argsort",
    "has_inf",
    "has_nan",
    "isfinite",
    "range",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_parameter(
    shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None
):
    helper = LayerHelper("create_parameter", name=name, param_attr=attr)
    return helper.create_parameter(
        helper.param_attr, shape, dtype, is_bias, default_initializer
    )


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    from paddle_tpu import initializer

    helper = LayerHelper("global_var", name=name)
    return helper.create_global_variable(
        shape=shape,
        dtype=dtype,
        persistable=persistable,
        name=name,
        initializer=initializer.ConstantInitializer(value),
    )


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": dtype},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, framework.Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
        return output
    return assign_numpy(np.asarray(input), output=output)


def assign_numpy(arr, output=None):
    helper = LayerHelper("assign_value")
    arr = np.asarray(arr)
    if output is None:
        output = helper.create_variable_for_type_inference(str(arr.dtype))
    helper.append_op(
        type="assign_value",
        outputs={"Out": [output]},
        attrs={
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "values": arr.flatten().tolist(),
        },
    )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op(
        type="reverse",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": list(axis)},
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    ids = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        type="argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis},
    )
    return out, ids


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_inf(x):
    helper = LayerHelper("isinf")
    out = helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op(type="isinf", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x):
    helper = LayerHelper("isnan")
    out = helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op(type="isnan", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="range",
        outputs={"Out": [out]},
        attrs={"start": start, "end": end, "step": step, "dtype": dtype},
    )
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y on the high side of each dim up to x's shape
    (pad_constant_like_op.cc)."""
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(
        type="pad_constant_like",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"pad_value": float(pad_value)},
    )
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (crop_op.cc): slice `shape` starting at `offsets`
    (defaults to zeros). Runtime-tensor shape/offsets are obviated under
    XLA static shapes — pass lists."""
    if shape is None or not isinstance(shape, (list, tuple)):
        raise TypeError("crop: shape must be a static list/tuple")
    offsets = list(offsets) if offsets is not None else [0] * len(shape)
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="crop",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "offsets": offsets},
    )
    return out


def sum(x, name=None):
    """Elementwise sum of a list of tensors (sum_op.cc layer surface;
    same op as the sums() helper above)."""
    return sums(x if isinstance(x, (list, tuple)) else [x])


def load(file_path, dtype=None, name=None):
    """Materialize a variable saved by fluid.io.save_vars (load_op.cc
    layer surface); the value is folded into the executable at trace
    time, cast to `dtype` when given (else the file's dtype), and the
    shape comes from the file via shape inference."""
    helper = LayerHelper("load", name=name)
    out = helper.create_variable_for_type_inference(dtype or "float32")
    helper.append_op(
        type="load",
        outputs={"Out": [out]},
        attrs={"file_path": file_path, "dtype": dtype or ""},
    )
    return out


__all__ += ["crop", "sum", "load"]
