"""Recurrent layers (python/paddle/fluid/layers/nn.py dynamic_lstm/
dynamic_lstmp/dynamic_gru/gru_unit parity).

Contract matches the reference: ``dynamic_lstm(input, size=4*D)`` expects the
caller to have projected the raw features with an ``fc`` of size 4*D (the
reference's lstm_op takes the x@W_x product as Input). The dense-padded
difference: ``input`` here is [batch, max_len, size] with an optional
``length`` tensor, instead of an LoD-packed flat tensor.
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm",
    "dynamic_lstmp",
    "dynamic_gru",
    "gru_unit",
    "lstm_unit",
    "row_conv",
]


def dynamic_lstm(
    input,
    size,
    length=None,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
):
    """LSTM over a padded sequence. ``size`` = 4 * hidden_dim.

    Reference: layers/nn.py dynamic_lstm -> lstm_op.cc.
    """
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    assert size % 4 == 0, "size must be 4 * hidden_dim"
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, 4 * hidden], dtype=dtype
    )
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden_out, cell_out


def dynamic_lstmp(
    input,
    size,
    proj_size,
    length=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    proj_activation="tanh",
    dtype="float32",
    name=None,
):
    """Projected LSTM (lstmp_op.cc). size = 4*hidden, proj_size = P."""
    helper = LayerHelper("lstmp", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    assert size % 4 == 0
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * hidden], dtype=dtype
    )
    proj_weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, proj_size], dtype=dtype
    )
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    proj_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "Input": [input],
        "Weight": [weight],
        "ProjWeight": [proj_weight],
        "Bias": [bias],
    }
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_lstmp",
        inputs=inputs,
        outputs={"Projection": [proj_out], "Cell": [cell_out]},
        attrs={
            "use_peepholes": use_peepholes,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "proj_activation": proj_activation,
        },
    )
    return proj_out, cell_out


def dynamic_gru(
    input,
    size,
    length=None,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    name=None,
):
    """GRU over a padded sequence. ``input`` is [B, T, 3*size]."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden


def gru_unit(
    input,
    hidden,
    size,
    param_attr=None,
    bias_attr=None,
    activation="tanh",
    gate_activation="sigmoid",
):
    """Single GRU step (gru_unit_op.cc); for StaticRNN bodies."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru_unit",
        inputs={
            "Input": [input],
            "HiddenPrev": [hidden],
            "Weight": [weight],
            "Bias": [bias],
        },
        outputs={
            "Gate": [gate],
            "ResetHiddenPrev": [reset_hidden_pre],
            "Hidden": [updated_hidden],
        },
        attrs={
            "activation": activation,
            "gate_activation": gate_activation,
        },
    )
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, c_prev, forget_bias=0.0, name=None):
    """Single LSTM step over pre-projected gates x_t=[B,4D] (lstm_unit_op)."""
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [x_t], "C_prev": [c_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)},
    )
    return h, c


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (row_conv_op.cc)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, int(input.shape[-1])]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [out]},
    )
    return helper.append_activation(out)
