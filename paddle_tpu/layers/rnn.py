"""Recurrent layers (python/paddle/fluid/layers/nn.py dynamic_lstm/
dynamic_lstmp/dynamic_gru/gru_unit parity).

Contract matches the reference: ``dynamic_lstm(input, size=4*D)`` expects the
caller to have projected the raw features with an ``fc`` of size 4*D (the
reference's lstm_op takes the x@W_x product as Input). The dense-padded
difference: ``input`` here is [batch, max_len, size] with an optional
``length`` tensor, instead of an LoD-packed flat tensor.
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm",
    "dynamic_lstmp",
    "dynamic_gru",
    "gru_unit",
    "lstm_unit",
    "row_conv",
    "attention_lstm_decoder",
    "attention_lstm_beam_decode",
    "beam_search",
    "beam_search_decode",
]


def dynamic_lstm(
    input,
    size,
    length=None,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
):
    """LSTM over a padded sequence. ``size`` = 4 * hidden_dim.

    Reference: layers/nn.py dynamic_lstm -> lstm_op.cc.
    """
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    assert size % 4 == 0, "size must be 4 * hidden_dim"
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, 4 * hidden], dtype=dtype
    )
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden_out, cell_out


def dynamic_lstmp(
    input,
    size,
    proj_size,
    length=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    proj_activation="tanh",
    dtype="float32",
    name=None,
):
    """Projected LSTM (lstmp_op.cc). size = 4*hidden, proj_size = P."""
    helper = LayerHelper("lstmp", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    assert size % 4 == 0
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * hidden], dtype=dtype
    )
    proj_weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, proj_size], dtype=dtype
    )
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    proj_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "Input": [input],
        "Weight": [weight],
        "ProjWeight": [proj_weight],
        "Bias": [bias],
    }
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_lstmp",
        inputs=inputs,
        outputs={"Projection": [proj_out], "Cell": [cell_out]},
        attrs={
            "use_peepholes": use_peepholes,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "proj_activation": proj_activation,
        },
    )
    return proj_out, cell_out


def dynamic_gru(
    input,
    size,
    length=None,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    name=None,
):
    """GRU over a padded sequence. ``input`` is [B, T, 3*size]."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden


def gru_unit(
    input,
    hidden,
    size,
    param_attr=None,
    bias_attr=None,
    activation="tanh",
    gate_activation="sigmoid",
):
    """Single GRU step (gru_unit_op.cc); for StaticRNN bodies."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru_unit",
        inputs={
            "Input": [input],
            "HiddenPrev": [hidden],
            "Weight": [weight],
            "Bias": [bias],
        },
        outputs={
            "Gate": [gate],
            "ResetHiddenPrev": [reset_hidden_pre],
            "Hidden": [updated_hidden],
        },
        attrs={
            "activation": activation,
            "gate_activation": gate_activation,
        },
    )
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, c_prev, forget_bias=0.0, name=None):
    """Single LSTM step over pre-projected gates x_t=[B,4D] (lstm_unit_op)."""
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [x_t], "C_prev": [c_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)},
    )
    return h, c


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (row_conv_op.cc)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, int(input.shape[-1])]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [out]},
    )
    return helper.append_activation(out)


def _decoder_params(helper, name, decoder_size, ctx_dim, emb_dim, vocab=None,
                    dtype="float32"):
    """Create (or reuse by name) the fused attention-decoder parameters.

    Fixed names keyed on ``name`` so a training program and a separately
    built generation program share the same weights through the scope
    (Fluid's param_attr-by-name sharing contract).
    """
    from paddle_tpu.param_attr import ParamAttr

    D = decoder_size

    def p(suffix, shape, is_bias=False):
        return helper.create_parameter(
            attr=ParamAttr(name="%s_%s" % (name, suffix)), shape=shape,
            dtype=dtype, is_bias=is_bias,
        )

    params = {
        "StateProjW": p("state_proj_w", [D, D]),
        "AttnW": p("attn_w", [2 * D, 1]),
        "CellW": p("cell_w", [D + ctx_dim + emb_dim, 4 * D]),
        "CellB": p("cell_b", [1, 4 * D], is_bias=True),
    }
    if vocab is not None:
        params["OutW"] = p("out_w", [D, vocab])
        # 1-D so the same named param is shared with the training program's
        # fc(num_flatten_dims=2) output projection bias.
        params["OutB"] = p("out_b", [vocab], is_bias=True)
    return params


def attention_lstm_decoder(
    target_embedding,
    encoder_vec,
    encoder_proj,
    decoder_boot,
    size,
    encoder_len=None,
    name="attention_decoder",
):
    """Teacher-forced attention-LSTM decoder (attention_lstm_op.cc parity).

    target_embedding [B, T, M]; encoder_vec [B, S, C]; encoder_proj
    [B, S, size]; decoder_boot [B, size]. Returns hidden states [B, T, size].
    """
    helper = LayerHelper("attention_lstm", name=name)
    dtype = target_embedding.dtype
    ctx_dim = int(encoder_vec.shape[-1])
    emb_dim = int(target_embedding.shape[-1])
    params = _decoder_params(helper, name, size, ctx_dim, emb_dim,
                             dtype=dtype)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    attn = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "X": [target_embedding],
        "EncoderVec": [encoder_vec],
        "EncoderProj": [encoder_proj],
        "H0": [decoder_boot],
    }
    inputs.update({k: [v] for k, v in params.items()})
    if encoder_len is not None:
        inputs["EncoderLen"] = [encoder_len]
    helper.append_op(
        type="attention_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell],
                 "AttentionWeight": [attn]},
    )
    return hidden


def attention_lstm_beam_decode(
    encoder_vec,
    encoder_proj,
    decoder_boot,
    embedding_param,
    size,
    vocab_size,
    beam_size=4,
    max_len=32,
    start_id=1,
    end_id=2,
    encoder_len=None,
    name="attention_decoder",
):
    """Whole-loop beam-search generation with the decoder named ``name``
    (shares weights with attention_lstm_decoder). Returns
    (sentence_ids [B, beam, max_len], sentence_scores [B, beam])."""
    helper = LayerHelper("attention_lstm_beam_decode", name=name)
    dtype = encoder_vec.dtype
    ctx_dim = int(encoder_vec.shape[-1])
    emb_dim = int(embedding_param.shape[-1])
    params = _decoder_params(helper, name, size, ctx_dim, emb_dim,
                             vocab=vocab_size, dtype=dtype)
    ids = helper.create_variable_for_type_inference("int32")
    scores = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "EncoderVec": [encoder_vec],
        "EncoderProj": [encoder_proj],
        "H0": [decoder_boot],
        "Embedding": [embedding_param],
    }
    inputs.update({k: [v] for k, v in params.items()})
    if encoder_len is not None:
        inputs["EncoderLen"] = [encoder_len]
    helper.append_op(
        type="attention_lstm_beam_decode",
        inputs=inputs,
        outputs={"SentenceIds": [ids], "SentenceScores": [scores]},
        attrs={
            "beam_size": int(beam_size),
            "max_len": int(max_len),
            "start_id": int(start_id),
            "end_id": int(end_id),
        },
    )
    return ids, scores


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id=0,
                is_accumulated=True, name=None):
    """One dense beam-search step (beam_search_op.cc parity).

    pre_ids/pre_scores [B, K]; scores [B, K, V] (accumulated log-probs, or
    per-step probabilities when is_accumulated=False). Returns
    (selected_ids, selected_scores, parent_idx), each [B, K]."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    sel_scores = helper.create_variable_for_type_inference(pre_scores.dtype)
    parent = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "scores": [scores]},
        outputs={"selected_ids": [sel_ids], "selected_scores": [sel_scores],
                 "parent_idx": [parent]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "is_accumulated": bool(is_accumulated)},
    )
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parent_idx, scores=None, beam_size=4, end_id=0,
                       name=None):
    """Backtrack stacked per-step beams ([T, B, K] ids/parents) into
    sentences [B, K, T] (beam_search_decode_op.cc parity). When the per-step
    selected scores ([T, B, K]) are passed, they are gathered along the same
    lattice and returned as per-token scores [B, K, T] (zeros otherwise)."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference(ids.dtype)
    sent_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"Ids": [ids], "ParentIdx": [parent_idx]}
    if scores is not None:
        inputs["Scores"] = [scores]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs={"SentenceIds": [sent_ids], "SentenceScores": [sent_scores]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)},
    )
    return sent_ids, sent_scores
