"""Structured-prediction / big-vocab NLP layers.

Reference parity: layers/nn.py linear_chain_crf, crf_decoding, warpctc,
ctc_greedy_decoder, edit_distance, chunk_eval, nce, hsigmoid (backed by the
ops in paddle_tpu/ops/{crf,ctc,sampling,metric}_ops.py).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "linear_chain_crf",
    "crf_decoding",
    "warpctc",
    "ctc_greedy_decoder",
    "edit_distance",
    "chunk_eval",
    "nce",
    "hsigmoid",
]


def linear_chain_crf(input, label, length=None, param_attr=None, name=None):
    """CRF NLL cost [B, 1]; creates the [num_tags+2, num_tags] transition
    parameter (rows 0/1 = start/stop weights)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr,
                         name=name)
    num_tags = int(input.shape[-1])
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype=input.dtype,
    )
    alpha = helper.create_variable_for_type_inference(input.dtype)
    emission_exps = helper.create_variable_for_type_inference(input.dtype)
    transition_exps = helper.create_variable_for_type_inference(input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf",
        inputs=inputs,
        outputs={
            "Alpha": [alpha],
            "EmissionExps": [emission_exps],
            "TransitionExps": [transition_exps],
            "LogLikelihood": [log_likelihood],
        },
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    """Viterbi path [B, T] (or 0/1 correctness when label is given); reuses
    the transition parameter created by linear_chain_crf via param_attr."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr, name=name)
    num_tags = int(input.shape[-1])
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype=input.dtype,
    )
    path = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="crf_decoding",
        inputs=inputs,
        outputs={"ViterbiPath": [path]},
    )
    return path


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None, name=None):
    """CTC loss [B, 1] over dense [B, T, V] logits (warpctc_op.cc)."""
    helper = LayerHelper("warpctc", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True
    )
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    helper.append_op(
        type="warpctc",
        inputs=inputs,
        outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
        attrs={"blank": int(blank), "norm_by_times": bool(norm_by_times)},
    )
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Argmax over classes then CTC collapse (ctc_align_op.cc). ``input``
    is [B, T, V] probabilities/logits; returns (paths [B, T], lengths)."""
    from paddle_tpu.layers import nn as nn_layers

    _, ids = nn_layers.topk(input, k=1)
    ids = nn_layers.reshape(ids, shape=[0, -1])  # [B, T]
    helper = LayerHelper("ctc_align", name=name)
    out = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    out_len = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True
    )
    inputs = {"Input": [ids]}
    if input_length is not None:
        inputs["InputLength"] = [input_length]
    helper.append_op(
        type="ctc_align",
        inputs=inputs,
        outputs={"Output": [out], "OutputLength": [out_len]},
        attrs={"blank": int(blank), "merge_repeated": True},
    )
    return out, out_len


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """Levenshtein distance per pair [B, 1] + sequence count."""
    helper = LayerHelper("edit_distance", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True
    )
    seq_num = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    inputs = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        inputs["HypsLength"] = [input_length]
    if label_length is not None:
        inputs["RefsLength"] = [label_length]
    helper.append_op(
        type="edit_distance",
        inputs=inputs,
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": bool(normalized)},
    )
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, length=None, name=None):
    """Chunk P/R/F1 (chunk_eval_op.cc). Returns (precision, recall, f1,
    num_infer, num_label, num_correct)."""
    helper = LayerHelper("chunk_eval", name=name)
    precision = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True
    )
    recall = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True
    )
    f1 = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True
    )
    num_infer = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    num_label = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    num_correct = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    inputs = {"Inference": [input], "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="chunk_eval",
        inputs=inputs,
        outputs={
            "Precision": [precision],
            "Recall": [recall],
            "F1-Score": [f1],
            "NumInferChunks": [num_infer],
            "NumLabelChunks": [num_label],
            "NumCorrectChunks": [num_correct],
        },
        attrs={
            "num_chunk_types": int(num_chunk_types),
            "chunk_scheme": chunk_scheme,
            "excluded_chunk_types": list(excluded_chunk_types or []),
        },
    )
    return precision, recall, f1, num_infer, num_label, num_correct


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", seed=0, is_sparse=False):
    """Noise-contrastive estimation cost [B, 1] (nce_op.cc)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = int(input.shape[-1])
    num_neg_samples = int(num_neg_samples or 10)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype,
    )
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_total_classes, 1],
            dtype=input.dtype, is_bias=True,
        )
        inputs["Bias"] = [b]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True
    )
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={
            "Cost": [cost],
            "SampleLogits": [sample_logits],
            "SampleLabels": [sample_labels],
        },
        attrs={
            "num_total_classes": int(num_total_classes),
            "num_neg_samples": num_neg_samples,
            "sampler": {"uniform": 0, "log_uniform": 1,
                        "custom_dist": 2}.get(sampler, 0),
            "seed": seed,
            "is_sparse": is_sparse,
        },
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical-sigmoid cost [B, 1] over a complete binary class tree
    (hierarchical_sigmoid_op.cc / math/matrix_bit_code)."""
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = int(input.shape[-1])
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim],
        dtype=input.dtype,
    )
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_classes - 1, 1],
            dtype=input.dtype, is_bias=True,
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": int(num_classes)},
    )
    return out
