"""Random / initializer ops (gaussian_random, uniform_random, dropout...).

Reference parity: paddle/fluid/operators/{gaussian_random,uniform_random,
truncated_gaussian_random,dropout,random_crop,sampling_id}_op.cc. Keys come
from the LowerContext's counter-based PRNG stream (stateless, TPU-friendly);
a nonzero ``seed`` attr pins the stream like the reference's fix_seed.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype

register_op(
    "gaussian_random",
    inputs=[],
    outputs=["Out"],
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": "float32"},
    lower=lambda ctx, ins, attrs: attrs.get("mean", 0.0)
    + attrs.get("std", 1.0)
    * jax.random.normal(
        ctx.rng(), tuple(attrs["shape"]), device_dtype(attrs.get("dtype"))
    ),
    grad=None,
)

register_op(
    "uniform_random",
    inputs=[],
    outputs=["Out"],
    attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0, "dtype": "float32"},
    lower=lambda ctx, ins, attrs: jax.random.uniform(
        ctx.rng(),
        tuple(attrs["shape"]),
        device_dtype(attrs.get("dtype")),
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
    ),
    grad=None,
)

register_op(
    "truncated_gaussian_random",
    inputs=[],
    outputs=["Out"],
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": "float32"},
    lower=lambda ctx, ins, attrs: attrs.get("mean", 0.0)
    + attrs.get("std", 1.0)
    * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, tuple(attrs["shape"]),
        device_dtype(attrs.get("dtype")),
    ),
    grad=None,
)


def _lower_dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    if ctx.is_test or attrs.get("is_test", False):
        # Downgrade-in-infer (reference default dropout_implementation).
        if attrs.get("dropout_implementation", "downgrade_in_infer") == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * jnp.asarray(1.0 - p, x.dtype), "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, jnp.shape(x))
    mask = keep.astype(x.dtype)
    if attrs.get("dropout_implementation", "downgrade_in_infer") == "upscale_in_train":
        if p >= 1.0:
            out = jnp.zeros_like(x)
        else:
            out = x * mask / jnp.asarray(1.0 - p, x.dtype)
    else:
        out = x * mask
    return {"Out": out, "Mask": mask}


register_op(
    "dropout",
    inputs=["X"],
    outputs=["Out", "Mask"],
    attrs={
        "dropout_prob": 0.5,
        "is_test": False,
        "seed": 0,
        "fix_seed": False,
        "dropout_implementation": "downgrade_in_infer",
    },
    lower=_lower_dropout,
    intermediate_outputs=("Mask",),
)

register_op(
    "sampling_id",
    inputs=["X"],
    outputs=["Out"],
    attrs={"min": 0.0, "max": 1.0, "seed": 0},
    lower=lambda ctx, ins, attrs: jax.random.categorical(
        ctx.rng(), jnp.log(jnp.maximum(ins["X"][0], 1e-20)), axis=-1
    ).astype(device_dtype("int64")),
    grad=None,
)

register_op(
    "random_crop",
    inputs=["X", "Seed"],
    outputs=["Out", "SeedOut"],
    attrs={"shape": [], "seed": 0},
    lower=lambda ctx, ins, attrs: {
        "Out": _random_crop(ctx, ins["X"][0], attrs["shape"]),
        "SeedOut": ins["Seed"][0],
    },
    grad=None,
)


def _random_crop(ctx, x, crop_shape):
    full = jnp.shape(x)
    nbatch_dims = len(full) - len(crop_shape)
    key = ctx.rng()
    starts = []
    for i, c in enumerate(crop_shape):
        limit = full[nbatch_dims + i] - c + 1
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, limit))
    start_idx = [jnp.zeros((), jnp.int32)] * nbatch_dims + starts
    sizes = list(full[:nbatch_dims]) + list(crop_shape)
    return jax.lax.dynamic_slice(x, start_idx, sizes)


def _batch_size_like_shape(ins, attrs):
    shape = list(attrs["shape"])
    x = ins["Input"][0]
    shape[attrs.get("output_dim_idx", 0)] = jnp.shape(x)[
        attrs.get("input_dim_idx", 0)
    ]
    return tuple(shape)


register_op(
    "gaussian_random_batch_size_like",
    inputs=["Input"],
    outputs=["Out"],
    attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
           "mean": 0.0, "std": 1.0, "seed": 0, "dtype": "float32"},
    lower=lambda ctx, ins, attrs: attrs.get("mean", 0.0)
    + attrs.get("std", 1.0)
    * jax.random.normal(
        ctx.rng(),
        _batch_size_like_shape(ins, attrs),
        device_dtype(attrs.get("dtype")),
    ),
    grad=None,
)

register_op(
    "uniform_random_batch_size_like",
    inputs=["Input"],
    outputs=["Out"],
    attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
           "min": -1.0, "max": 1.0, "seed": 0, "dtype": "float32"},
    lower=lambda ctx, ins, attrs: jax.random.uniform(
        ctx.rng(),
        _batch_size_like_shape(ins, attrs),
        device_dtype(attrs.get("dtype")),
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
    ),
    grad=None,
)
