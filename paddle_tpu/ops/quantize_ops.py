"""Quantization-aware-training ops: fake quantize / dequantize.

Reference parity: paddle/fluid/operators/fake_quantize_op.cc
(FakeQuantizeAbsMaxOp :124, FakeQuantizeRangeAbsMaxOp :184) and
fake_dequantize_op.cc. The reference pairs these with a dedicated grad op
that passes gradients straight through the rounding; here the lowering
writes the quantized value as ``x + stop_gradient(q - x)`` so the
vjp-synthesized ``<op>_grad`` is exactly that straight-through estimator —
no custom grad machinery needed.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op


def _quant_range(bit_length):
    return float((1 << (bit_length - 1)) - 1)


def _quantize(x, scale, bit_length, clip=True):
    """Quantize with a full straight-through estimator: the forward value
    is round(clip(x/scale)*range) but the backward pass is d(out)/d(x) =
    range/scale everywhere — the reference grad kernel passes dout through
    unconditionally, including for clipped elements."""
    rng = _quant_range(bit_length)
    scale = jnp.maximum(scale, jnp.asarray(1e-8, x.dtype))
    y = x / scale * rng
    q = y
    if clip:
        q = jnp.clip(x / scale, -1.0, 1.0) * rng
    return y + jax.lax.stop_gradient(jnp.round(q) - y)


def _lower_fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    # no clip needed: |x| <= scale by construction
    return {
        "Out": _quantize(x, scale, bit_length, clip=False),
        "OutScale": jnp.reshape(scale, (1,)),
    }


register_op(
    "fake_quantize_abs_max",
    inputs=["X"],
    outputs=["Out", "OutScale"],
    attrs={"bit_length": 8},
    lower=_lower_fake_quantize_abs_max,
    intermediate_outputs=("OutScale",),
)


def _lower_fake_quantize_range_abs_max(ctx, ins, attrs):
    """Running-range variant: in training the scale is the max of the
    incoming scale and the current batch's abs-max (a monotone envelope —
    the windowed decay of the reference needs host state and is noted as
    approximated); at test time the stored scale is used unchanged."""
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    in_scale = jnp.reshape(ins["InScale"][0], ())
    if ctx.is_test or attrs.get("is_test", False):
        scale = in_scale
    else:
        scale = jnp.maximum(in_scale, jnp.max(jnp.abs(x)))
    scale = jax.lax.stop_gradient(scale)
    return {
        "Out": _quantize(x, scale, bit_length),
        "OutScale": jnp.reshape(scale, (1,)),
    }


register_op(
    "fake_quantize_range_abs_max",
    inputs=["X", "InScale"],
    outputs=["Out", "OutScale"],
    attrs={"bit_length": 8, "window_size": 10000, "is_test": False},
    lower=_lower_fake_quantize_range_abs_max,
    no_grad_inputs=("InScale",),
    intermediate_outputs=("OutScale",),
)


def _lower_fake_dequantize_max_abs(ctx, ins, attrs):
    x = ins["X"][0]
    scale = jnp.reshape(ins["Scale"][0], ())
    return x.astype(scale.dtype) * scale / attrs.get("max_range", 127.0)


register_op(
    "fake_dequantize_max_abs",
    inputs=["X", "Scale"],
    outputs=["Out"],
    attrs={"max_range": 127.0},
    lower=_lower_fake_dequantize_max_abs,
    no_grad_inputs=("Scale",),
)


def _lower_dequantize_weight(ctx, ins, attrs):
    """int8-storage weight dequantization: Out = X_int8 * step, where
    ``step`` (= scale / max_range) was computed by convert_to_int8. The
    deployment counterpart of the reference's convert_to_int8
    (contrib/quantize/quantize_transpiler.py:348): the model dir stores
    int8 tensors; the serving graph rehydrates floats on load, XLA folds
    the multiply into the weight constant after the first step."""
    x = ins["X"][0]
    step = jnp.reshape(ins["Scale"][0], ())
    return x.astype(step.dtype) * step


register_op(
    "dequantize_weight",
    inputs=["X", "Scale"],
    outputs=["Out"],
    attrs={},
    lower=_lower_dequantize_weight,
    no_grad_inputs=("X", "Scale"),
)
