"""Comparison/logical ops and control-flow support ops.

Reference parity: paddle/fluid/operators/{compare,logical,increment,
conditional_block,while}_op.cc and array ops. The sub-block mega-ops
(while/conditional_block) lower through the BlockLowerer into
lax.while_loop / lax.cond — XLA-compilable control flow instead of nested
host Executors with StepScopes.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op

for _name, _fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
]:
    register_op(
        _name,
        inputs=["X", "Y"],
        outputs=["Out"],
        attrs={"axis": -1},
        lower=(lambda f: lambda ctx, ins, attrs: f(ins["X"][0], ins["Y"][0]))(_fn),
        grad=None,
    )

for _name, _fn in [
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    register_op(
        _name,
        inputs=["X", "Y"],
        outputs=["Out"],
        lower=(lambda f: lambda ctx, ins, attrs: f(ins["X"][0], ins["Y"][0]))(_fn),
        grad=None,
    )

register_op(
    "logical_not",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.logical_not(ins["X"][0]),
    grad=None,
)

register_op(
    "increment",
    inputs=["X"],
    outputs=["Out"],
    attrs={"step": 1.0},
    lower=lambda ctx, ins, attrs: ins["X"][0]
    + jnp.asarray(attrs.get("step", 1.0), ins["X"][0].dtype),
    grad=None,
)

register_op(
    "is_empty",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(
        jnp.asarray(ins["X"][0].size == 0), (1,)
    ),
    grad=None,
)


# while / cond / recurrent sub-block mega-ops live in
# paddle_tpu/ops/subblock_ops.py (lax.while_loop / lax.cond / lax.scan).


def _lower_where_select(ctx, ins, attrs):
    """Batch-element select: Cond [batch, 1] bool picks X rows else Y rows.

    The XLA-friendly merge behind the IfElse layer (reference splits the
    batch with split_lod_tensor and re-merges, conditional_block_op.cc /
    split_lod_tensor_op.cc); a select is the dense equivalent.
    """
    cond = ins["Cond"][0]
    x, y = ins["X"][0], ins["Y"][0]
    c = jnp.reshape(cond, (-1,) + (1,) * (x.ndim - 1)).astype(bool)
    return jnp.where(c, x, y)


register_op(
    "where_select",
    inputs=["Cond", "X", "Y"],
    outputs=["Out"],
    lower=_lower_where_select,
    no_grad_inputs=("Cond",),
)
