"""Comparison/logical ops and control-flow support ops.

Reference parity: paddle/fluid/operators/{compare,logical,increment,
conditional_block,while}_op.cc and array ops. The sub-block mega-ops
(while/conditional_block) lower through the BlockLowerer into
lax.while_loop / lax.cond — XLA-compilable control flow instead of nested
host Executors with StepScopes.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op

for _name, _fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
]:
    register_op(
        _name,
        inputs=["X", "Y"],
        outputs=["Out"],
        attrs={"axis": -1},
        lower=(lambda f: lambda ctx, ins, attrs: f(ins["X"][0], ins["Y"][0]))(_fn),
        grad=None,
    )

for _name, _fn in [
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    register_op(
        _name,
        inputs=["X", "Y"],
        outputs=["Out"],
        lower=(lambda f: lambda ctx, ins, attrs: f(ins["X"][0], ins["Y"][0]))(_fn),
        grad=None,
    )

register_op(
    "logical_not",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.logical_not(ins["X"][0]),
    grad=None,
)

register_op(
    "increment",
    inputs=["X"],
    outputs=["Out"],
    attrs={"step": 1.0},
    lower=lambda ctx, ins, attrs: ins["X"][0]
    + jnp.asarray(attrs.get("step", 1.0), ins["X"][0].dtype),
    grad=None,
)

register_op(
    "is_empty",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(
        jnp.asarray(ins["X"][0].size == 0), (1,)
    ),
    grad=None,
)


def _lower_while(ctx, ins, attrs):
    """while_op (while_op.cc:36): runs sub_block until Condition is false.

    TPU-first lowering: the loop-carried state is every variable that the
    sub-block writes AND that exists before the loop (plus the condition
    var); the body is the sub-block lowered functionally. Requires
    shape-invariant carries (XLA constraint) — Fluid programs that grow
    tensor arrays per-iteration must use the scan-based DynamicRNN path.
    """
    raise NotImplementedError(
        "while lowering is driven by the executor via sub-block capture; "
        "see paddle_tpu/ops/subblock_ops.py"
    )


register_op(
    "while",
    inputs=["*X", "Condition"],
    outputs=["*Out", "StepScopes"],
    attrs={"sub_block": -1},
    lower=_lower_while,
    grad=None,
)


def _lower_conditional_block(ctx, ins, attrs):
    raise NotImplementedError(
        "conditional_block lowering is driven by the executor; "
        "see paddle_tpu/ops/subblock_ops.py"
    )


register_op(
    "conditional_block",
    inputs=["*X", "Cond"],
    outputs=["*Out", "Scope"],
    attrs={"sub_block": -1, "is_scalar_condition": False},
    lower=_lower_conditional_block,
    grad=None,
)
