"""Mixture-of-Experts FFN with expert parallelism.

The reference framework predates MoE entirely (like long-context —
SURVEY.md §5.7); this is the TPU-native design that provides the expert
(ep) axis of the parallelism story. Switch-Transformer-style routing in
fully static shapes (XLA requirement): top-1/top-2 gating, a fixed
per-expert capacity, einsum dispatch/combine tensors instead of
scatter/gather, and the load-balancing auxiliary loss.

Expert parallelism falls out of GSPMD: the stacked expert weights
[E, ...] are sharded on dim 0 over a mesh axis
(ParallelExecutor(sharding_overrides={"...moe...w": ("expert", ...)})),
the [E, C, D] dispatched activations inherit that sharding, and XLA
inserts the all-to-alls — no hand-written token exchange.

Routing is non-differentiable by design (argmax); gradients flow through
the gate probabilities via the combine weights, exactly the Switch
Transformer formulation.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op

_ACTS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "identity": lambda v: v,
}


def _route_one(probs, base, capacity, valid=None):
    """Route each token to its best remaining expert. probs: [N, E]
    (zeroed at experts already used by earlier routes); base: [E] queue
    occupancy from earlier routes; valid: optional [N] token validity
    (invalid tokens occupy no queue slots). Returns (expert_idx [N],
    gate [N], gate_raw [N], dispatch [N, E, C] one-hot with
    over-capacity tokens dropped, new base)."""
    n, e = probs.shape
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(expert, e, dtype=probs.dtype)  # [N, E]
    if valid is not None:
        onehot = onehot * valid[:, None]
    # Position of each token within its expert's queue, in token order —
    # the static-shape stand-in for a scatter with overflow dropping.
    # Earlier routes' assignments (incl. dropped ones) advance the queue,
    # so routes never collide in the [E, C] buffer.
    pos = jnp.cumsum(onehot, axis=0) - onehot + base[None, :]  # [N, E]
    pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N]
    keep = pos_tok < capacity
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(pos_tok, capacity, dtype=probs.dtype)[:, None, :]
        * keep[:, None, None]
    )  # [N, E, C]
    return (expert, gate * keep, gate, dispatch,
            base + jnp.sum(onehot, axis=0))


def _lower_moe_ffn(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, D] or [N, D]
    gate_w = ins["GateW"][0]  # [D, E]
    w1 = ins["ExpertW1"][0]  # [E, D, H]
    b1 = ins["ExpertB1"][0]  # [E, H]
    w2 = ins["ExpertW2"][0]  # [E, H, D]
    b2 = ins["ExpertB2"][0]  # [E, D]
    tok_mask = ins.get("Mask", [None])[0]  # optional [B, T] validity
    top_k = int(attrs.get("top_k", 1))
    cap_factor = float(attrs.get("capacity_factor", 1.25))
    act = _ACTS[attrs.get("act", "gelu")]

    orig_shape = jnp.shape(x)
    d = orig_shape[-1]
    xf = jnp.reshape(x, (-1, d))  # [N, D]
    n = xf.shape[0]
    e = gate_w.shape[1]
    capacity = max(1, int(cap_factor * n * top_k / e))

    logits = (xf @ gate_w).astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    if tok_mask is not None:
        # Padding tokens must not route: they would consume shared expert
        # capacity (dropping REAL tokens' outputs) and dominate the
        # load-balancing statistics. Zeroing their probs gives them gate
        # 0 everywhere; _route_one's onehot is also zeroed below so they
        # occupy no queue slots.
        valid = (jnp.reshape(tok_mask, (-1,)) > 0).astype(probs.dtype)
        probs = probs * valid[:, None]
    else:
        valid = None

    combines = []
    used = jnp.zeros_like(probs)
    masked = probs
    base = jnp.zeros((e,), probs.dtype)
    for _ in range(top_k):
        expert, gate, gate_raw, dispatch, base = _route_one(
            masked, base, capacity, valid)
        combines.append((gate, gate_raw, dispatch))
        used = used + jax.nn.one_hot(expert, e, dtype=probs.dtype)
        masked = probs * (1.0 - used)
    if top_k > 1:
        # Switch/GShard renormalization: divide by the sum of the
        # SELECTED (pre-drop) gates, so a token whose second route
        # overflowed keeps weight g1/(g1+g2) on the surviving expert —
        # not full weight 1.0.
        total = sum(g_raw for _, g_raw, _ in combines) + 1e-9
        combines = [(g / total, g_raw, disp)
                    for g, g_raw, disp in combines]

    # One dispatch/combine pair covers all k routes.
    dispatch = sum(disp for _, _, disp in combines)  # [N, E, C]
    combine = sum(
        g[:, None, None] * disp for g, _, disp in combines
    )  # [N, E, C]

    xe = jnp.einsum(
        "nec,nd->ecd", dispatch.astype(x.dtype), xf
    )  # [E, C, D]
    h = act(
        jnp.einsum("ecd,edh->ech", xe, w1) + b1[:, None, :]
    )
    ye = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]  # [E, C, D]
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), ye)

    # Switch load-balancing loss: E * sum_e f_e * P_e, where f_e is the
    # fraction of tokens whose TOP-1 router choice is expert e — the
    # PRE-capacity-drop assignment (switch_transformer paper eq. 4).
    # Computing f from the post-drop dispatch would cap it at
    # capacity/N, saturating the loss exactly when routing collapses
    # onto one expert and it needs the strongest push. With a token
    # mask, both statistics run over VALID tokens only.
    top1 = jnp.argmax(probs, axis=-1)
    oh1 = jax.nn.one_hot(top1, e, dtype=jnp.float32)
    if valid is not None:
        oh1 = oh1 * valid[:, None]
        denom = jnp.maximum(jnp.sum(valid), 1.0)
    else:
        denom = float(n)
    f = jnp.sum(oh1, axis=0) / denom
    p = jnp.sum(probs, axis=0) / denom
    aux = e * jnp.sum(f * p)

    return {
        "Out": jnp.reshape(out, orig_shape),
        "AuxLoss": jnp.reshape(aux.astype(x.dtype), (1,)),
    }


register_op(
    "moe_ffn",
    inputs=["X", "GateW", "ExpertW1", "ExpertB1", "ExpertW2", "ExpertB2",
            "Mask"],
    outputs=["Out", "AuxLoss"],
    attrs={"top_k": 1, "capacity_factor": 1.25, "act": "gelu"},
    lower=_lower_moe_ffn,
    grad="auto",
    no_grad_inputs=("Mask",),
)
