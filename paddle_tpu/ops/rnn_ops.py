"""Recurrent ops lowered to lax.scan.

Reference parity: ``paddle/fluid/operators/lstm_op.cc``, ``gru_op.cc``,
``lstm_unit_op.cc``, ``gru_unit_op.cc``, ``row_conv_op.cc``. The reference
batches LoD-packed sequences via ``operators/math/sequence2batch.h`` and
runs per-timestep fused CPU/CUDA kernels (``math/lstm_compute``,
``math/gru_compute``); on TPU the idiomatic form is a dense-padded
[batch, max_len, d] tensor with an optional Length input, scanned over the
time axis with ``lax.scan`` so XLA unrolls/pipelines the recurrence and the
per-step matmul lands on the MXU. Gradients come from jax.vjp over the whole
scan (the registry's auto-grad), which is exactly scan's reverse pass —
no StepScopes replay needed (SURVEY.md §7 hard part (g)).

Dense-shape contract (differs from the reference's LoD packing by design):
  Input: [batch, T, gates*D]   (projected input, i.e. x @ W_x, as in the
                                reference where the user applies fc first)
  Weight: recurrence weights   Bias: [1, gates*D] (+peephole cols for lstm)
  Length: optional [batch] int lengths for masking.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _act(name):
    return _ACTS[name or "tanh"]


def _time_major(x):
    # [B, T, ...] -> [T, B, ...]
    return jnp.moveaxis(x, 1, 0)


def _batch_major(x):
    return jnp.moveaxis(x, 0, 1)


def _step_mask(ins, x):
    """[T, B, 1] float mask from optional Length input ([B] lengths)."""
    if "Length" in ins and ins["Length"]:
        lens = jnp.reshape(ins["Length"][0], (-1,))
        T = jnp.shape(x)[1]
        m = (jnp.arange(T)[:, None] < lens[None, :]).astype(x.dtype)
        return m[:, :, None]
    return None


def _masked(new, old, m_t):
    if m_t is None:
        return new
    return new * m_t + old * (1.0 - m_t)


# ---------------------------------------------------------------------------
# dynamic_lstm  (lstm_op.cc)
# ---------------------------------------------------------------------------


def _lower_dynamic_lstm(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, T, 4D]
    w = ins["Weight"][0]  # [D, 4D]
    B, T = jnp.shape(x)[0], jnp.shape(x)[1]
    D = jnp.shape(w)[0]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    use_peepholes = attrs.get("use_peepholes", True)

    bias = ins.get("Bias", [None])[0]
    if bias is not None:
        bias = jnp.reshape(bias, (-1,))
        b_gate = bias[: 4 * D]
        if use_peepholes:
            w_ic = bias[4 * D: 5 * D]
            w_fc = bias[5 * D: 6 * D]
            w_oc = bias[6 * D: 7 * D]
        else:
            w_ic = w_fc = w_oc = None
    else:
        b_gate = jnp.zeros((4 * D,), x.dtype)
        w_ic = w_fc = w_oc = None

    h0 = ins.get("H0", [None])[0]
    c0 = ins.get("C0", [None])[0]
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), x.dtype)

    xs = _time_major(x)  # [T, B, 4D]
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, axis=0)
    mask = _step_mask(ins, x)
    if attrs.get("is_reverse", False) and mask is not None:
        mask = jnp.flip(mask, axis=0)

    def cell_fn(carry, xm):
        h_prev, c_prev = carry
        xt, m_t = xm
        gates = xt + h_prev @ w + b_gate  # [B, 4D]
        gi = gates[:, 0 * D:1 * D]
        gf = gates[:, 1 * D:2 * D]
        gc = gates[:, 2 * D:3 * D]
        go = gates[:, 3 * D:4 * D]
        if w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c_prev + i * cand_act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        h_new = _masked(h_new, h_prev, m_t)
        c_new = _masked(c_new, c_prev, m_t)
        return (h_new, c_new), (h_new, c_new)

    from paddle_tpu import flags as _flags

    no_init_state = (ins.get("H0", [None])[0] is None
                     and ins.get("C0", [None])[0] is None)
    # kernel starts from zero state; any activation the op accepts is
    # also in the kernel's table, so no further gating is needed
    if _flags.get("use_pallas_lstm") and no_init_state:
        # fused Pallas recurrence (kernels/lstm_cell.py): h/c live in
        # VMEM across timesteps; the scan below is the reference path
        from paddle_tpu.kernels.lstm_cell import fused_lstm

        xw_bt = _batch_major(xs)  # [B, T', 4D] (already reversed if set)
        m_bt = (_batch_major(mask[:, :, 0]) if mask is not None else None)
        peep = ((w_ic, w_fc, w_oc) if w_ic is not None else None)
        hid, cel = fused_lstm(
            xw_bt, w, b_gate, peephole=peep, mask=m_bt,
            gate_act=attrs.get("gate_activation", "sigmoid"),
            cell_act=attrs.get("cell_activation", "tanh"),
            cand_act=attrs.get("candidate_activation", "tanh"),
        )
        if attrs.get("is_reverse", False):
            hid = jnp.flip(hid, axis=1)
            cel = jnp.flip(cel, axis=1)
        return {"Hidden": hid, "Cell": cel}

    ms = mask if mask is not None else jnp.ones((T, 1, 1), x.dtype)
    (_, _), (hs, cs) = jax.lax.scan(cell_fn, (h0, c0), (xs, ms))
    if attrs.get("is_reverse", False):
        hs = jnp.flip(hs, axis=0)
        cs = jnp.flip(cs, axis=0)
    return {"Hidden": _batch_major(hs), "Cell": _batch_major(cs)}


register_op(
    "dynamic_lstm",
    inputs=["Input", "H0", "C0", "Weight", "Bias", "Length"],
    outputs=["Hidden", "Cell"],
    attrs={
        "use_peepholes": True,
        "is_reverse": False,
        "gate_activation": "sigmoid",
        "cell_activation": "tanh",
        "candidate_activation": "tanh",
    },
    lower=_lower_dynamic_lstm,
    no_grad_inputs=("Length",),
)


# ---------------------------------------------------------------------------
# dynamic_lstmp  (lstmp_op.cc — LSTM with a recurrent projection layer)
# ---------------------------------------------------------------------------


def _lower_dynamic_lstmp(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, T, 4D]
    w = ins["Weight"][0]  # [P, 4D] recurrence over projected state
    w_proj = ins["ProjWeight"][0]  # [D, P]
    B = jnp.shape(x)[0]
    D = jnp.shape(w_proj)[0]
    P = jnp.shape(w_proj)[1]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "identity"))
    use_peepholes = attrs.get("use_peepholes", True)

    bias = ins.get("Bias", [None])[0]
    if bias is not None:
        bias = jnp.reshape(bias, (-1,))
        b_gate = bias[: 4 * D]
        if use_peepholes:
            w_ic = bias[4 * D: 5 * D]
            w_fc = bias[5 * D: 6 * D]
            w_oc = bias[6 * D: 7 * D]
        else:
            w_ic = w_fc = w_oc = None
    else:
        b_gate = jnp.zeros((4 * D,), x.dtype)
        w_ic = w_fc = w_oc = None

    r0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, D), x.dtype)
    xs = _time_major(x)
    mask = _step_mask(ins, x)
    ms = mask if mask is not None else jnp.ones(
        (jnp.shape(x)[1], 1, 1), x.dtype
    )

    def cell_fn(carry, xm):
        r_prev, c_prev = carry
        xt, m_t = xm
        gates = xt + r_prev @ w + b_gate
        gi = gates[:, 0 * D:1 * D]
        gf = gates[:, 1 * D:2 * D]
        gc = gates[:, 2 * D:3 * D]
        go = gates[:, 3 * D:4 * D]
        if w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c_prev + i * cand_act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        r_new = _masked(r_new, r_prev, m_t)
        c_new = _masked(c_new, c_prev, m_t)
        return (r_new, c_new), (r_new, c_new)

    (_, _), (rs, cs) = jax.lax.scan(cell_fn, (r0, c0), (xs, ms))
    return {"Projection": _batch_major(rs), "Cell": _batch_major(cs)}


register_op(
    "dynamic_lstmp",
    inputs=["Input", "Weight", "ProjWeight", "Bias", "Length"],
    outputs=["Projection", "Cell"],
    attrs={
        "use_peepholes": True,
        "gate_activation": "sigmoid",
        "cell_activation": "tanh",
        "candidate_activation": "tanh",
        "proj_activation": "identity",
    },
    lower=_lower_dynamic_lstmp,
    no_grad_inputs=("Length",),
)


# ---------------------------------------------------------------------------
# dynamic_gru  (gru_op.cc)
# ---------------------------------------------------------------------------


def _lower_dynamic_gru(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, T, 3D]
    w = ins["Weight"][0]  # [D, 3D]: [:, :2D] gate weights, [:, 2D:] candidate
    B = jnp.shape(x)[0]
    D = jnp.shape(w)[0]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))

    bias = ins.get("Bias", [None])[0]
    b = (
        jnp.reshape(bias, (-1,))
        if bias is not None
        else jnp.zeros((3 * D,), x.dtype)
    )
    w_g = w[:, : 2 * D]
    w_c = w[:, 2 * D:]

    h0 = ins.get("H0", [None])[0]
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)

    xs = _time_major(x)
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, axis=0)
    mask = _step_mask(ins, x)
    if attrs.get("is_reverse", False) and mask is not None:
        mask = jnp.flip(mask, axis=0)
    ms = mask if mask is not None else jnp.ones(
        (jnp.shape(x)[1], 1, 1), x.dtype
    )

    def cell_fn(h_prev, xm):
        xt, m_t = xm
        g = xt[:, : 2 * D] + h_prev @ w_g + b[: 2 * D]
        u = gate_act(g[:, :D])
        r = gate_act(g[:, D:])
        c = cand_act(xt[:, 2 * D:] + (r * h_prev) @ w_c + b[2 * D:])
        h_new = u * h_prev + (1.0 - u) * c
        h_new = _masked(h_new, h_prev, m_t)
        return h_new, h_new

    from paddle_tpu import flags as _flags

    if _flags.get("use_pallas_gru") and ins.get("H0", [None])[0] is None:
        # fused Pallas recurrence (kernels/gru_cell.py); scan is reference
        from paddle_tpu.kernels.gru_cell import fused_gru

        hid = fused_gru(
            _batch_major(xs), w_g, w_c, b,
            mask=(_batch_major(mask[:, :, 0]) if mask is not None
                  else None),
            gate_act=attrs.get("gate_activation", "sigmoid"),
            cand_act=attrs.get("activation", "tanh"),
        )
        if attrs.get("is_reverse", False):
            hid = jnp.flip(hid, axis=1)
        return {"Hidden": hid}

    _, hs = jax.lax.scan(cell_fn, h0, (xs, ms))
    if attrs.get("is_reverse", False):
        hs = jnp.flip(hs, axis=0)
    return {"Hidden": _batch_major(hs)}


register_op(
    "dynamic_gru",
    inputs=["Input", "H0", "Weight", "Bias", "Length"],
    outputs=["Hidden"],
    attrs={
        "is_reverse": False,
        "gate_activation": "sigmoid",
        "activation": "tanh",
    },
    lower=_lower_dynamic_gru,
    no_grad_inputs=("Length",),
)


# ---------------------------------------------------------------------------
# single-step units (lstm_unit_op.cc, gru_unit_op.cc) — building blocks for
# StaticRNN-style user-composed recurrences.
# ---------------------------------------------------------------------------


def _lower_lstm_unit(ctx, ins, attrs):
    x = ins["X"][0]  # [B, 4D] pre-projected gates
    c_prev = ins["C_prev"][0]  # [B, D]
    D = jnp.shape(c_prev)[1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, 0 * D:1 * D])
    f = jax.nn.sigmoid(x[:, 1 * D:2 * D] + forget_bias)
    g = jnp.tanh(x[:, 2 * D:3 * D])
    o = jax.nn.sigmoid(x[:, 3 * D:4 * D])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


register_op(
    "lstm_unit",
    inputs=["X", "C_prev"],
    outputs=["C", "H"],
    attrs={"forget_bias": 0.0},
    lower=_lower_lstm_unit,
)


def _lower_gru_unit(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, 3D] projected input
    h_prev = ins["HiddenPrev"][0]  # [B, D]
    w = ins["Weight"][0]  # [D, 3D]
    D = jnp.shape(h_prev)[1]
    bias = ins.get("Bias", [None])[0]
    b = (
        jnp.reshape(bias, (-1,))
        if bias is not None
        else jnp.zeros((3 * D,), x.dtype)
    )
    gate_act = _act(
        {1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
            attrs.get("gate_activation", 1), "sigmoid"
        )
        if isinstance(attrs.get("gate_activation", 1), int)
        else attrs.get("gate_activation", "sigmoid")
    )
    cand_act = _act(
        {1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
            attrs.get("activation", 2), "tanh"
        )
        if isinstance(attrs.get("activation", 2), int)
        else attrs.get("activation", "tanh")
    )
    g = x[:, : 2 * D] + h_prev @ w[:, : 2 * D] + b[: 2 * D]
    u = gate_act(g[:, :D])
    r = gate_act(g[:, D:])
    c = cand_act(x[:, 2 * D:] + (r * h_prev) @ w[:, 2 * D:] + b[2 * D:])
    h = u * h_prev + (1.0 - u) * c
    gate = jnp.concatenate([u, r, c], axis=1)
    return {"Gate": gate, "ResetHiddenPrev": r * h_prev, "Hidden": h}


register_op(
    "gru_unit",
    inputs=["Input", "HiddenPrev", "Weight", "Bias"],
    outputs=["Gate", "ResetHiddenPrev", "Hidden"],
    attrs={"activation": 2, "gate_activation": 1},
    lower=_lower_gru_unit,
    intermediate_outputs=("Gate", "ResetHiddenPrev"),
)


# ---------------------------------------------------------------------------
# row_conv (row_conv_op.cc — lookahead convolution for streaming ASR)
# ---------------------------------------------------------------------------


def _lower_row_conv(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, D]
    f = ins["Filter"][0]  # [future_context + 1, D]
    k = jnp.shape(f)[0]
    T = jnp.shape(x)[1]
    # out[t] = sum_{j=0..k-1} x[t+j] * f[j]  (zero past the end)
    padded = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(int(k)):
        out = out + padded[:, j:j + T, :] * f[j][None, None, :]
    return {"Out": out}


register_op(
    "row_conv",
    inputs=["X", "Filter"],
    outputs=["Out"],
    lower=_lower_row_conv,
)
