"""Speculative-decoding ops: tree write, accept walk, survivor commit.

The verify side of speculative decoding over the paged slot pool
(serving/generation.py ``SlotDecodeSession(speculative=...)``): a host
drafter proposes K tokens per slot as a speculation TREE (node 0 is the
anchor — the slot's current token — and draft node ``i`` extends node
``parent[i]``); the target model scores every node in one dispatch
through ``paged_tree_attention``; then ``slot_speculative_accept``
replays the EXACT sequential sampling rule down the tree and commits
the longest draft prefix the target itself would have emitted, plus
one correction/bonus token.

Bit-exactness contract: the accept walk samples each position through
``sampling_ops.sample_step_tokens`` — the same token-choice core, with
the same (seed, slot, position) PRNG key scheme, that the plain
``slot_decode_sample`` step uses — and advances the slot lifecycle
through the shared ``slot_lifecycle_advance`` formula. The committed
stream is therefore bit-identical to the ``FLAGS_speculative=off``
sequential stream (greedy exact, sampled via the key scheme); the
drafter only decides how MANY of those tokens land per dispatch, never
WHICH tokens.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype
from paddle_tpu.ops.sampling_ops import (
    sample_step_tokens,
    slot_lifecycle_advance,
)


def _lower_paged_spec_kv_write(ctx, ins, attrs):
    """Tree write: land all N tree nodes' K/V rows into the slot's
    write pages at storage positions ``pos .. pos + N - 1`` (node 0 —
    the anchor — at ``pos``, exactly where the plain step would write
    it). Done slots pass an all-trash table row, and rows past the
    table's coverage trash-route inside the kernel helper."""
    from paddle_tpu.kernels.paged_attention import paged_kv_write_block

    k_pool = ins["KPool"][0]
    v_pool = ins["VPool"][0]
    k_new = ins["KNew"][0]  # [S, H, N, dh]
    v_new = ins["VNew"][0]
    S, H, N, dh = k_new.shape
    pos = jnp.reshape(ins["Pos"][0], (-1, 1)).astype(jnp.int32)
    table = jnp.reshape(ins["PageTable"][0], (S, -1)).astype(jnp.int32)
    positions = pos + jnp.arange(N, dtype=jnp.int32)[None, :]
    k_out, v_out = paged_kv_write_block(
        k_pool, v_pool, k_new, v_new, table, positions)
    return {"KOut": k_out, "VOut": v_out}


register_op(
    "paged_spec_kv_write",
    inputs=["KPool", "VPool", "KNew", "VNew", "PageTable", "Pos"],
    outputs=["KOut", "VOut"],
    lower=_lower_paged_spec_kv_write,
    grad=None,
    no_grad_inputs=("PageTable", "Pos"),
)


def _lower_paged_spec_kv_compact(ctx, ins, attrs):
    """Survivor commit: move accepted path nodes' K/V rows to their
    canonical storage positions (``base + j`` gets node ``path[j]``'s
    row for ``1 <= j < accept_len``). Rejected branches' rows are
    simply left behind past the new resident length — never attended
    again, overwritten by the next dispatch's tree."""
    from paddle_tpu.kernels.paged_attention import paged_kv_compact

    k_pool = ins["KPool"][0]
    v_pool = ins["VPool"][0]
    path = ins["Path"][0]
    S = path.shape[0]
    table = jnp.reshape(ins["PageTable"][0], (S, -1)).astype(jnp.int32)
    base = jnp.reshape(ins["Pos"][0], (-1,)).astype(jnp.int32)
    acc = jnp.reshape(ins["AcceptLen"][0], (-1,)).astype(jnp.int32)
    k_out, v_out = paged_kv_compact(
        k_pool, v_pool, table, base, jnp.reshape(path, (S, -1)), acc)
    return {"KOut": k_out, "VOut": v_out}


register_op(
    "paged_spec_kv_compact",
    inputs=["KPool", "VPool", "PageTable", "Pos", "Path", "AcceptLen"],
    outputs=["KOut", "VOut"],
    lower=_lower_paged_spec_kv_compact,
    grad=None,
    no_grad_inputs=("PageTable", "Pos", "Path", "AcceptLen"),
)


def _lower_slot_speculative_accept(ctx, ins, attrs):
    """The in-graph accept/reject walk. Per slot, starting at the
    anchor (node 0, sequence position ``pos``):

    1. sample token ``u`` from the current node's logits with the
       sequential rule (``sample_step_tokens`` at the node's sequence
       position);
    2. commit ``u`` and advance the lifecycle via the shared
       ``slot_lifecycle_advance`` (done latches on eos / budget);
    3. if some draft child of the current node carries exactly ``u``
       (and its storage position is inside the decode budget), descend
       into it and repeat — otherwise stop: ``u`` was the correction
       (or bonus) token and becomes the next dispatch's anchor.

    Every live slot commits at least 1 token (the plain step's rate)
    and at most N. Entries of ``TokSeq`` past ``AcceptLen`` are eos
    padding, same as the multi-step fetch contract. ``Path[j]`` names
    the tree node whose K/V row backs committed token ``j`` (for
    ``1 <= j < AcceptLen``; identity elsewhere) — the
    ``paged_spec_kv_compact`` gather map. ``Out`` is the new anchor
    token (eos for done slots, the ``slot_decode_sample`` forcing
    rule)."""
    lg = ins["Logits"][0].astype(jnp.float32)  # [S, N, V]
    S, N, _V = lg.shape
    nodes = jnp.reshape(ins["Nodes"][0], (S, N))
    parent = jnp.reshape(ins["Parent"][0], (S, N)).astype(jnp.int32)
    pos = ins["Pos"][0]
    pos_flat = jnp.reshape(pos, (-1,))
    done_in = ins["Done"][0]
    was_done = jnp.reshape(done_in, (-1,)) > 0
    strategy = attrs.get("strategy", "greedy")
    temperature = float(attrs.get("temperature", 1.0))
    top_k = int(attrs.get("top_k", 0))
    base_seed = int(attrs.get("base_seed", 0))
    eos = int(attrs.get("eos_id", 2))
    max_len = int(attrs.get("max_length", 0))
    if max_len < 2:
        raise ValueError(
            "slot_speculative_accept: max_length attr must be >= 2 "
            "(the decode budget), got %d" % max_len)
    idt = device_dtype("int64")

    cur = jnp.zeros((S,), jnp.int32)
    posq = pos_flat
    done_s = was_done
    stopped = was_done  # a finished slot never walks
    acc_len = jnp.zeros((S,), jnp.int32)
    path = jnp.tile(jnp.arange(N, dtype=jnp.int32)[None, :], (S, 1))
    j_idx = jnp.arange(N, dtype=jnp.int32)[None, :]
    tok_cols = []
    # N is small and static: unrolled walk, one sequential-sampling
    # replay per level
    for d in range(N):
        active = jnp.logical_not(stopped)
        lg_cur = lg[jnp.arange(S), cur]  # [S, V]
        u = sample_step_tokens(lg_cur, posq, strategy, temperature,
                               top_k, base_seed)
        adv_pos, adv_done = slot_lifecycle_advance(
            posq, done_s, u, eos, max_len)
        new_posq = jnp.where(active, adv_pos, posq)
        new_done = jnp.where(active, adv_done, done_s)
        # draft child carrying the target's own token, storage in budget
        match = ((parent == cur[:, None]) & (j_idx >= 1)
                 & (nodes.astype(idt) == u[:, None])
                 & (pos_flat.astype(jnp.int32)[:, None] + j_idx < max_len))
        has_child = jnp.any(match, axis=1)
        child = jnp.argmax(match, axis=1).astype(jnp.int32)
        cont = active & jnp.logical_not(new_done) & has_child
        if d + 1 < N:
            path = path.at[:, d + 1].set(
                jnp.where(cont, child, path[:, d + 1]))
        tok_cols.append(jnp.where(active, u, jnp.asarray(eos, idt)))
        acc_len = acc_len + active.astype(jnp.int32)
        stopped = stopped | (active & jnp.logical_not(cont))
        cur = jnp.where(cont, child, cur)
        posq = new_posq
        done_s = new_done

    toks = jnp.stack(tok_cols, axis=1)  # [S, N]
    last = jnp.clip(acc_len - 1, 0, N - 1)
    anchor = jnp.where(acc_len > 0, toks[jnp.arange(S), last],
                       jnp.asarray(eos, idt))
    return {
        "Out": anchor[:, None],
        "TokSeq": toks,
        "AcceptLen": acc_len.astype(idt)[:, None],
        "Path": path.astype(idt),
        "PosOut": jnp.reshape(posq, jnp.shape(pos)).astype(
            pos_flat.dtype),
        "DoneOut": done_s.astype(idt)[:, None],
    }


register_op(
    "slot_speculative_accept",
    inputs=["Logits", "Nodes", "Parent", "Pos", "Done"],
    outputs=["Out", "TokSeq", "AcceptLen", "Path", "PosOut", "DoneOut"],
    attrs={"strategy": "greedy", "temperature": 1.0, "top_k": 0,
           "base_seed": 0, "eos_id": 2, "max_length": 0},
    lower=_lower_slot_speculative_accept,
    grad=None,
    no_grad_inputs=("Nodes", "Parent", "Pos", "Done"),
)
