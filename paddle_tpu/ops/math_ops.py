"""Dense math ops: mul/matmul/elementwise/reduce/scale/sum/...

Reference parity: paddle/fluid/operators/{mul,matmul,elementwise_*,reduce_*,
scale,sum,clip,cumsum,...}_op.cc — each lowered to XLA instead of
cuBLAS/Eigen kernels. Matmuls run in the input dtype (bf16 stays bf16 on
the MXU with float32 accumulation via XLA's default precision).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.ops.common import broadcast_y, flatten_to_2d, reduce_axes, to_dtype


def _lower_mul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = flatten_to_2d(x, xn)
    y2 = flatten_to_2d(y, yn)
    out = x2 @ y2
    out_shape = tuple(jnp.shape(x)[:xn]) + tuple(jnp.shape(y)[yn:])
    return jnp.reshape(out, out_shape)


register_op(
    "mul",
    inputs=["X", "Y"],
    outputs=["Out"],
    attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
    lower=_lower_mul,
)


def _lower_matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if jnp.ndim(x) > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if jnp.ndim(y) > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return out


register_op(
    "matmul",
    inputs=["X", "Y"],
    outputs=["Out"],
    attrs={"transpose_X": False, "transpose_Y": False, "alpha": 1.0},
    lower=_lower_matmul,
)


def _elementwise(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        y = broadcast_y(x, y, attrs.get("axis", -1))
        return fn(x, y)

    return lower


for _name, _fn in [
    ("elementwise_add", jnp.add),
    ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply),
    ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum),
    ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
    ("elementwise_mod", jnp.mod),
    ("elementwise_floordiv", jnp.floor_divide),
]:
    register_op(
        _name,
        inputs=["X", "Y"],
        outputs=["Out"],
        attrs={"axis": -1},
        lower=_elementwise(_fn),
    )


register_op(
    "sum",
    inputs=["*X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: sum(ins["X"][1:], ins["X"][0]),
)

register_op(
    "scale",
    inputs=["X"],
    outputs=["Out"],
    attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True},
    lower=lambda ctx, ins, attrs: (
        ins["X"][0] * jnp.asarray(attrs.get("scale", 1.0), ins["X"][0].dtype)
        + jnp.asarray(attrs.get("bias", 0.0), ins["X"][0].dtype)
        if attrs.get("bias_after_scale", True)
        else (ins["X"][0] + jnp.asarray(attrs.get("bias", 0.0), ins["X"][0].dtype))
        * jnp.asarray(attrs.get("scale", 1.0), ins["X"][0].dtype)
    ),
)

register_op(
    "mean",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(jnp.mean(ins["X"][0]), (1,)),
)


def _reduce(fn):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        axes = reduce_axes(
            jnp.ndim(x), attrs.get("dim", [0]), attrs.get("reduce_all", False)
        )
        out = fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if jnp.ndim(out) == 0:
            out = jnp.reshape(out, (1,))
        return out

    return lower


for _name, _fn in [
    ("reduce_sum", jnp.sum),
    ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max),
    ("reduce_min", jnp.min),
    ("reduce_prod", jnp.prod),
]:
    register_op(
        _name,
        inputs=["X"],
        outputs=["Out"],
        attrs={"dim": [0], "keep_dim": False, "reduce_all": False},
        lower=_reduce(_fn),
    )

register_op(
    "clip",
    inputs=["X"],
    outputs=["Out"],
    attrs={"min": 0.0, "max": 0.0},
    lower=lambda ctx, ins, attrs: jnp.clip(
        ins["X"][0],
        jnp.asarray(attrs["min"], ins["X"][0].dtype),
        jnp.asarray(attrs["max"], ins["X"][0].dtype),
    ),
)

register_op(
    "clip_by_norm",
    inputs=["X"],
    outputs=["Out"],
    attrs={"max_norm": 1.0},
    lower=lambda ctx, ins, attrs: _clip_by_norm(ins["X"][0], attrs["max_norm"]),
)


def _clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    max_norm = jnp.asarray(max_norm, x.dtype)
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


register_op(
    "cumsum",
    inputs=["X"],
    outputs=["Out"],
    attrs={"axis": -1, "exclusive": False, "reverse": False},
    lower=lambda ctx, ins, attrs: _cumsum(ins["X"][0], attrs),
)


def _cumsum(x, attrs):
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis)
    return out


register_op(
    "l2_normalize",
    inputs=["X"],
    outputs=["Out", "Norm"],
    attrs={"axis": -1, "epsilon": 1e-10},
    lower=lambda ctx, ins, attrs: _l2_normalize(ins["X"][0], attrs),
    intermediate_outputs=("Norm",),
)


def _l2_normalize(x, attrs):
    axis = attrs.get("axis", -1)
    eps = jnp.asarray(attrs.get("epsilon", 1e-10), x.dtype)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return x / norm, norm


register_op(
    "norm",
    inputs=["X"],
    outputs=["Out", "Norm"],
    attrs={"axis": 1, "epsilon": 1e-10},
    lower=lambda ctx, ins, attrs: _l2_normalize(ins["X"][0], attrs),
    intermediate_outputs=("Norm",),
)


def _lower_isfinite(ctx, ins, attrs):
    flat = [jnp.all(jnp.isfinite(x)) for x in ins["X"]]
    return jnp.reshape(jnp.stack(flat).all(), (1,))


register_op("isfinite", inputs=["*X"], outputs=["Out"], lower=_lower_isfinite, grad=None)

register_op(
    "isinf",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(
        jnp.any(jnp.isinf(ins["X"][0])), (1,)
    ),
    grad=None,
)

register_op(
    "isnan",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(
        jnp.any(jnp.isnan(ins["X"][0])), (1,)
    ),
    grad=None,
)


def _lower_cos_sim(ctx, ins, attrs):
    # cos_sim_op.cc: per-sample cosine similarity with all trailing dims
    # flattened (rows are dim 0); Y may have a single row (broadcast against
    # every row of X). Output is [N, 1].
    x = ins["X"][0]
    y = ins["Y"][0]
    x = jnp.reshape(x, (jnp.shape(x)[0], -1))
    y = jnp.reshape(y, (jnp.shape(y)[0], -1))
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    dot = jnp.sum(x * y, axis=1, keepdims=True)
    out = dot / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


register_op(
    "cos_sim",
    inputs=["X", "Y"],
    outputs=["Out", "XNorm", "YNorm"],
    lower=_lower_cos_sim,
    intermediate_outputs=("XNorm", "YNorm"),
)


def _lower_minus(ctx, ins, attrs):
    """minus_op.cc: Out = X - Y (kept as its own schema; the v2 layer
    surface exposes it separately from elementwise_sub)."""
    return ins["X"][0] - ins["Y"][0]


register_op(
    "minus",
    inputs=["X", "Y"],
    outputs=["Out"],
    lower=_lower_minus,
)


def _lower_l1_norm(ctx, ins, attrs):
    """l1_norm_op.cc: scalar sum of absolute values."""
    return jnp.reshape(jnp.sum(jnp.abs(ins["X"][0])), (1,))


register_op(
    "l1_norm",
    inputs=["X"],
    outputs=["Out"],
    lower=_lower_l1_norm,
)


def _lower_multiplex(ctx, ins, attrs):
    """multiplex_op.cc: per-row select among the candidate tensors —
    Out[b] = X[Ids[b]][b]. Lowering: stack candidates on a new axis and
    take_along_axis with the row index (one fused gather on TPU)."""
    ids = jnp.reshape(ins["Ids"][0], (-1,)).astype(jnp.int32)
    xs = jnp.stack(ins["X"], axis=0)  # [K, B, ...]
    b = xs.shape[1]
    idx = jnp.reshape(ids, (1, b) + (1,) * (xs.ndim - 2))
    return jnp.squeeze(
        jnp.take_along_axis(xs, jnp.broadcast_to(idx, (1,) + xs.shape[1:]),
                            axis=0),
        axis=0,
    )


register_op(
    "multiplex",
    inputs=["Ids", "*X"],
    outputs=["Out"],
    lower=_lower_multiplex,
    no_grad_inputs=("Ids",),
)


def _lower_bilinear_tensor_product(ctx, ins, attrs):
    """bilinear_tensor_product_op.cc: Out[b,k] = X[b]^T W_k Y[b] (+bias);
    one einsum so XLA maps it onto batched MXU matmuls."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["Weight"][0]  # [K, M, N]
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if "Bias" in ins and ins["Bias"]:
        out = out + jnp.reshape(ins["Bias"][0], (1, -1))
    return out


register_op(
    "bilinear_tensor_product",
    inputs=["X", "Y", "Weight", "Bias"],
    outputs=["Out"],
    lower=_lower_bilinear_tensor_product,
)


def _lower_conv_shift(ctx, ins, attrs):
    """conv_shift_op.cc (NTM circular convolution): X [B,M], Y [B,N] with
    N odd; Out[b,i] = sum_j X[b, (i + j - (N-1)/2) mod M] * Y[b,j].
    Lowered as a static modular gather + one einsum (no scalar loops)."""
    x = ins["X"][0]
    y = ins["Y"][0]
    m = x.shape[1]
    n = y.shape[1]
    half = (n - 1) // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    # windows[b, i, j] = X[b, idx[i, j]]
    windows = x[:, idx]
    return jnp.einsum("bij,bj->bi", windows, y)


register_op(
    "conv_shift",
    inputs=["X", "Y"],
    outputs=["Out"],
    lower=_lower_conv_shift,
)
