"""Sampled/factorized softmax ops: NCE + hierarchical sigmoid.

Reference parity: ``paddle/fluid/operators/nce_op.cc`` (noise-contrastive
estimation with a host-side Sampler) and ``hierarchical_sigmoid_op.cc``
(complete-binary-tree sigmoid via operators/math/matrix_bit_code). Both are
the reference's big-vocab softmax escape hatches; on TPU the sampled logits
are small gather+matmul batches and negative sampling uses the op's own
PRNG key (deterministic per program seed, like the reference's fixed-seed
Sampler option).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype


def _lower_nce(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, D]
    w = ins["Weight"][0]  # [V, D]
    label = jnp.reshape(ins["Label"][0], (jnp.shape(x)[0], -1))  # [B, Nt]
    bias = ins.get("Bias", [None])[0]
    num_total = int(attrs.get("num_total_classes", jnp.shape(w)[0]))
    num_neg = int(attrs.get("num_neg_samples", 10))
    B = jnp.shape(x)[0]
    n_true = jnp.shape(label)[1]

    if int(attrs.get("sampler", 0)) != 0:
        raise NotImplementedError(
            "nce: only the uniform sampler is lowered; log_uniform/"
            "custom_dist need their own noise-probability correction"
        )
    neg = jax.random.randint(ctx.rng(), (B, num_neg), 0, num_total)
    samples = jnp.concatenate([label, neg], axis=1)  # [B, Nt+Nn]
    w_s = w[samples]  # [B, S, D]
    logits = jnp.einsum("bd,bsd->bs", x, w_s)
    if bias is not None:
        logits = logits + jnp.reshape(bias, (-1,))[samples]
    # Uniform noise distribution q = 1/V; NCE logistic correction
    # log(k * q(y)).
    log_kq = jnp.log(num_neg / num_total)
    adjusted = logits - log_kq
    # Logistic NCE: -log sigma(s) for true classes (averaged), -log(1 -
    # sigma(s)) for each sampled negative.
    true_adj = adjusted[:, :n_true]
    neg_adj = adjusted[:, n_true:]
    cost = (
        jnp.sum(jax.nn.softplus(-true_adj), axis=1, keepdims=True) / n_true
        + jnp.sum(jax.nn.softplus(neg_adj), axis=1, keepdims=True)
    )
    sample_weight = ins.get("SampleWeight", [None])[0]
    if sample_weight is not None:
        cost = cost * jnp.reshape(sample_weight, (-1, 1))
    return {
        "Cost": cost,
        "SampleLogits": logits,
        "SampleLabels": samples.astype(device_dtype("int64")),
    }


register_op(
    "nce",
    inputs=["Input", "Label", "Weight", "Bias", "SampleWeight"],
    outputs=["Cost", "SampleLogits", "SampleLabels"],
    attrs={
        "num_total_classes": 0,
        "num_neg_samples": 10,
        "sampler": 0,
        "seed": 0,
        "is_sparse": False,
    },
    lower=_lower_nce,
    no_grad_inputs=("Label", "SampleWeight"),
    intermediate_outputs=("SampleLogits", "SampleLabels"),
)


def _lower_hierarchical_sigmoid(ctx, ins, attrs):
    x = ins["X"][0]  # [B, D]
    w = ins["W"][0]  # [num_classes - 1, D] internal-node weights
    label = jnp.reshape(ins["Label"][0], (-1,))  # [B]
    bias = ins.get("Bias", [None])[0]
    num_classes = int(attrs.get("num_classes", jnp.shape(w)[0] + 1))
    B = jnp.shape(x)[0]

    # Complete binary tree in heap order: leaf for class c is node
    # c + num_classes; internal nodes 1..num_classes-1 (weight row node-1).
    code = label.astype(jnp.int32) + num_classes
    max_depth = max(1, int(num_classes - 1).bit_length())

    losses = jnp.zeros((B, 1), x.dtype)
    pre_out = []
    for j in range(max_depth, 0, -1):
        node = code >> j  # internal node at this level
        valid = node >= 1
        bit = (code >> (j - 1)) & 1  # which child the path takes
        row = jnp.clip(node - 1, 0, num_classes - 2)
        s = jnp.einsum("bd,bd->b", x, w[row])
        if bias is not None:
            s = s + jnp.reshape(bias, (-1,))[row]
        # -log P(bit | node): softplus(s) - bit * s.
        step_loss = jax.nn.softplus(s) - bit.astype(s.dtype) * s
        losses = losses + jnp.where(valid, step_loss, 0.0)[:, None]
        pre_out.append(jnp.where(valid, s, 0.0))
    return {
        "Out": losses,
        "PreOut": jnp.stack(pre_out, axis=1),
    }


register_op(
    "hierarchical_sigmoid",
    inputs=["X", "W", "Label", "Bias"],
    outputs=["Out", "PreOut"],
    attrs={"num_classes": 2},
    lower=_lower_hierarchical_sigmoid,
    no_grad_inputs=("Label",),
    intermediate_outputs=("PreOut",),
)


def slot_lifecycle_advance(pos_flat, was_done, tok, eos, max_len):
    """The slot-pool lifecycle arithmetic shared by the sampling decode
    (``slot_decode_sample`` below) and the beam decode
    (``beam_search_ops._lower_slot_beam_search``): a live slot advances
    to ``pos + 1`` (clamped so the KV write for a max-length slot stays
    in bounds), a finished slot freezes, and the done latch trips on
    eos or on exhausting the ``max_len`` decode budget. All inputs are
    flat ``[S]`` arrays; returns ``(new_pos, new_done)`` (bool done).
    Keeping this ONE function is what makes a beam slot's lifecycle
    bit-identical to a sampler slot's — the host mirrors in
    ``serving.generation`` replay the same formula."""
    nxt_pos = jnp.minimum(pos_flat + 1, max_len - 1)
    new_pos = jnp.where(was_done, pos_flat, nxt_pos)
    new_done = (was_done | (tok == eos)
                | (pos_flat + 1 >= max_len - 1))
    return new_pos, new_done


def sample_step_tokens(lg, pos_flat, strategy, temperature, top_k,
                       base_seed):
    """The token-choice core of ``slot_decode_sample``, shared with the
    speculative accept walk (``speculative_ops``): greedy argmax, or
    temperature/top-k sampling keyed on ``fold_in(fold_in(
    PRNGKey(base_seed), slot), position)``. ``lg`` is ``[S, V]``
    float32 logits, ``pos_flat`` the per-slot SEQUENCE position being
    sampled at. Because the key depends only on (seed, slot, position)
    — never on how the token loop is partitioned into dispatches — a
    speculative verify that samples each accepted position through this
    function emits tokens bit-identical to the sequential stream.
    Returns flat ``[S]`` tokens (device int dtype, no done forcing)."""
    idt = device_dtype("int64")
    if strategy == "greedy" or temperature <= 0.0:
        return jnp.argmax(lg, axis=-1).astype(idt)
    S = lg.shape[0]
    scaled = lg / temperature
    if strategy == "top_k" and top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    base = jax.random.PRNGKey(int(base_seed))
    keys = jax.vmap(
        lambda i, p: jax.random.fold_in(jax.random.fold_in(base, i), p)
    )(jnp.arange(S), pos_flat.astype(jnp.int32))
    return jax.vmap(jax.random.categorical)(keys, scaled).astype(idt)


def _lower_slot_decode_sample(ctx, ins, attrs):
    """Batched per-slot token selection for the serving decode loop
    (serving/generation.py): greedy argmax, temperature, or top-k
    sampling over ``[S, 1, V]`` logits — plus the slot lifecycle
    arithmetic that lets a ``steps=K`` on-device scan advance every
    slot without host intervention (eos forcing for finished slots,
    clamped position advance, the done latch).

    Determinism contract: the PRNG stream is keyed on
    ``fold_in(fold_in(PRNGKey(base_seed), slot), position)`` — NOT the
    executor's per-dispatch step key — so a seeded replay is
    bit-identical regardless of how the token loop is partitioned into
    dispatches (K=1 host stepping and K=8 on-device scans sample the
    same tokens).
    """
    lg = ins["Logits"][0][:, 0, :].astype(jnp.float32)  # [S, V]
    pos = ins["Pos"][0]
    pos_flat = jnp.reshape(pos, (-1,))
    done_in = ins.get("Done", [None])[0]
    S = lg.shape[0]
    strategy = attrs.get("strategy", "greedy")
    temperature = float(attrs.get("temperature", 1.0))
    top_k = int(attrs.get("top_k", 0))
    eos = int(attrs.get("eos_id", 2))
    max_len = int(attrs.get("max_length", 0))
    if max_len < 2:
        raise ValueError(
            "slot_decode_sample: max_length attr must be >= 2 (the "
            "decode budget; positions clamp to max_length - 1), got %d"
            % max_len)
    idt = device_dtype("int64")
    tok = sample_step_tokens(lg, pos_flat, strategy, temperature, top_k,
                             int(attrs.get("base_seed", 0)))
    if done_in is not None:
        was_done = jnp.reshape(done_in, (-1,)) > 0
        tok = jnp.where(was_done, jnp.asarray(eos, idt), tok)
    else:
        was_done = jnp.zeros((S,), jnp.bool_)
    # position advance mirrors the host slot manager exactly (shared
    # with the beam decode through slot_lifecycle_advance)
    new_pos, new_done = slot_lifecycle_advance(
        pos_flat, was_done, tok, eos, max_len)
    return {
        "Out": tok[:, None],
        "PosOut": jnp.reshape(new_pos, jnp.shape(pos)).astype(
            pos_flat.dtype),
        "DoneOut": new_done.astype(idt)[:, None],
    }


register_op(
    "slot_decode_sample",
    inputs=["Logits", "Pos", "Done"],
    outputs=["Out", "PosOut", "DoneOut"],
    attrs={"strategy": "greedy", "temperature": 1.0, "top_k": 0,
           "base_seed": 0, "eos_id": 2, "max_length": 0},
    lower=_lower_slot_decode_sample,
    grad=None,
    no_grad_inputs=("Pos", "Done"),
)
