"""Sampled/factorized softmax ops: NCE + hierarchical sigmoid.

Reference parity: ``paddle/fluid/operators/nce_op.cc`` (noise-contrastive
estimation with a host-side Sampler) and ``hierarchical_sigmoid_op.cc``
(complete-binary-tree sigmoid via operators/math/matrix_bit_code). Both are
the reference's big-vocab softmax escape hatches; on TPU the sampled logits
are small gather+matmul batches and negative sampling uses the op's own
PRNG key (deterministic per program seed, like the reference's fixed-seed
Sampler option).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype


def _lower_nce(ctx, ins, attrs):
    x = ins["Input"][0]  # [B, D]
    w = ins["Weight"][0]  # [V, D]
    label = jnp.reshape(ins["Label"][0], (jnp.shape(x)[0], -1))  # [B, Nt]
    bias = ins.get("Bias", [None])[0]
    num_total = int(attrs.get("num_total_classes", jnp.shape(w)[0]))
    num_neg = int(attrs.get("num_neg_samples", 10))
    B = jnp.shape(x)[0]
    n_true = jnp.shape(label)[1]

    if int(attrs.get("sampler", 0)) != 0:
        raise NotImplementedError(
            "nce: only the uniform sampler is lowered; log_uniform/"
            "custom_dist need their own noise-probability correction"
        )
    neg = jax.random.randint(ctx.rng(), (B, num_neg), 0, num_total)
    samples = jnp.concatenate([label, neg], axis=1)  # [B, Nt+Nn]
    w_s = w[samples]  # [B, S, D]
    logits = jnp.einsum("bd,bsd->bs", x, w_s)
    if bias is not None:
        logits = logits + jnp.reshape(bias, (-1,))[samples]
    # Uniform noise distribution q = 1/V; NCE logistic correction
    # log(k * q(y)).
    log_kq = jnp.log(num_neg / num_total)
    adjusted = logits - log_kq
    # Logistic NCE: -log sigma(s) for true classes (averaged), -log(1 -
    # sigma(s)) for each sampled negative.
    true_adj = adjusted[:, :n_true]
    neg_adj = adjusted[:, n_true:]
    cost = (
        jnp.sum(jax.nn.softplus(-true_adj), axis=1, keepdims=True) / n_true
        + jnp.sum(jax.nn.softplus(neg_adj), axis=1, keepdims=True)
    )
    sample_weight = ins.get("SampleWeight", [None])[0]
    if sample_weight is not None:
        cost = cost * jnp.reshape(sample_weight, (-1, 1))
    return {
        "Cost": cost,
        "SampleLogits": logits,
        "SampleLabels": samples.astype(device_dtype("int64")),
    }


register_op(
    "nce",
    inputs=["Input", "Label", "Weight", "Bias", "SampleWeight"],
    outputs=["Cost", "SampleLogits", "SampleLabels"],
    attrs={
        "num_total_classes": 0,
        "num_neg_samples": 10,
        "sampler": 0,
        "seed": 0,
        "is_sparse": False,
    },
    lower=_lower_nce,
    no_grad_inputs=("Label", "SampleWeight"),
    intermediate_outputs=("SampleLogits", "SampleLabels"),
)


def _lower_hierarchical_sigmoid(ctx, ins, attrs):
    x = ins["X"][0]  # [B, D]
    w = ins["W"][0]  # [num_classes - 1, D] internal-node weights
    label = jnp.reshape(ins["Label"][0], (-1,))  # [B]
    bias = ins.get("Bias", [None])[0]
    num_classes = int(attrs.get("num_classes", jnp.shape(w)[0] + 1))
    B = jnp.shape(x)[0]

    # Complete binary tree in heap order: leaf for class c is node
    # c + num_classes; internal nodes 1..num_classes-1 (weight row node-1).
    code = label.astype(jnp.int32) + num_classes
    max_depth = max(1, int(num_classes - 1).bit_length())

    losses = jnp.zeros((B, 1), x.dtype)
    pre_out = []
    for j in range(max_depth, 0, -1):
        node = code >> j  # internal node at this level
        valid = node >= 1
        bit = (code >> (j - 1)) & 1  # which child the path takes
        row = jnp.clip(node - 1, 0, num_classes - 2)
        s = jnp.einsum("bd,bd->b", x, w[row])
        if bias is not None:
            s = s + jnp.reshape(bias, (-1,))[row]
        # -log P(bit | node): softplus(s) - bit * s.
        step_loss = jax.nn.softplus(s) - bit.astype(s.dtype) * s
        losses = losses + jnp.where(valid, step_loss, 0.0)[:, None]
        pre_out.append(jnp.where(valid, s, 0.0))
    return {
        "Out": losses,
        "PreOut": jnp.stack(pre_out, axis=1),
    }


register_op(
    "hierarchical_sigmoid",
    inputs=["X", "W", "Label", "Bias"],
    outputs=["Out", "PreOut"],
    attrs={"num_classes": 2},
    lower=_lower_hierarchical_sigmoid,
    no_grad_inputs=("Label",),
    intermediate_outputs=("PreOut",),
)
