"""Loss ops: cross_entropy, softmax_with_cross_entropy, regression losses.

Reference parity: paddle/fluid/operators/{cross_entropy,softmax_with_cross_
entropy,sigmoid_cross_entropy_with_logits,smooth_l1_loss,squared_l2_distance,
huber_loss,hinge_loss,log_loss,rank_loss,margin_rank_loss}_op.cc
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op


def _label_to_int(label):
    if jnp.ndim(label) > 1 and jnp.shape(label)[-1] == 1:
        label = jnp.squeeze(label, -1)
    return label.astype(jnp.int32)


def _lower_softmax_xent(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    log_softmax = logits - lse
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_softmax, axis=-1, keepdims=True)
    else:
        lbl = _label_to_int(label)
        nll = -jnp.take_along_axis(log_softmax, lbl[..., None], axis=-1)
        ignore = attrs.get("ignore_index", -100)
        if ignore >= 0:
            nll = jnp.where((lbl == ignore)[..., None], jnp.zeros_like(nll), nll)
        loss = nll
    return {"Softmax": jnp.exp(log_softmax), "Loss": loss}


register_op(
    "softmax_with_cross_entropy",
    inputs=["Logits", "Label"],
    outputs=["Softmax", "Loss"],
    attrs={"soft_label": False, "ignore_index": -100, "numeric_stable_mode": True},
    lower=_lower_softmax_xent,
    no_grad_inputs=("Label",),
    intermediate_outputs=("Softmax",),
)


def _lower_fused_label_smooth_ce(ctx, ins, attrs):
    """Single-pass label-smoothed cross entropy over the vocab dim.

    The composed head (softmax_with_cross_entropy + log_softmax +
    scale/add, models/transformer.py) makes ~5 logits-shaped passes and
    — because those ops are AMP-blacklisted — materializes them in f32:
    ~10 GB/step of HBM traffic at bench shapes (docs/MFU_PLAN.md lever
    #1, from the committed cost-model artifacts). This op keeps the
    logits in their network dtype (bf16 under AMP) and uses the
    factored identity

        L = lse - (1-eps) * x_y - (eps/V) * sum_i x_i

    so the smoothing term needs only sum(x) — no second log-softmax
    pass — with every reduction f32-accumulated (fused into one pass by
    XLA; no f32 logits-shaped tensor exists). The hand-written backward
    is the single fused expression

        dL/dx_i = (softmax_i - eps/V - (1-eps) * 1[i=y]) * g

    (exact: d lse = softmax, d x_y = onehot, d sum = 1). One bf16
    [N, V] write instead of the composed head's f32 chain.

    Reference capability anchor: softmax_with_cross_entropy_op.cc +
    label_smooth_op.cc composed; the fusion itself is TPU-motivated.
    """
    logits, label = ins["Logits"][0], ins["Label"][0]
    eps = float(attrs.get("epsilon", 0.0))
    vocab = int(jnp.shape(logits)[-1])
    lbl = _label_to_int(label)

    def fwd(x, l):
        m = jnp.max(x, axis=-1, keepdims=True)
        s = jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True,
                    dtype=jnp.float32)
        lse = m.astype(jnp.float32) + jnp.log(s)
        xy = jnp.take_along_axis(x, l[..., None], axis=-1)
        sumx = jnp.sum(x, axis=-1, keepdims=True, dtype=jnp.float32)
        loss = (lse - (1.0 - eps) * xy.astype(jnp.float32)
                - (eps / vocab) * sumx)
        return loss, (x, l, m, s)

    def bwd(res, g):
        x, l, m, s = res
        softmax = jnp.exp(x - m) / s.astype(x.dtype)
        onehot = jax.nn.one_hot(l, vocab, dtype=x.dtype)
        dx = (softmax - eps / vocab - (1.0 - eps) * onehot) \
            * g.astype(x.dtype)
        return (dx, None)

    f = jax.custom_vjp(lambda x, l: fwd(x, l)[0])
    f.defvjp(fwd, bwd)
    return {"Loss": f(logits, lbl)}


register_op(
    "fused_label_smooth_ce",
    inputs=["Logits", "Label"],
    outputs=["Loss"],
    attrs={"epsilon": 0.0},
    lower=_lower_fused_label_smooth_ce,
    no_grad_inputs=("Label",),
)


def _lower_cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        lbl = _label_to_int(label)
        p = jnp.take_along_axis(x, lbl[..., None], axis=-1)
        loss = -jnp.log(jnp.maximum(p, eps))
    return loss


register_op(
    "cross_entropy",
    inputs=["X", "Label"],
    outputs=["Y"],
    attrs={"soft_label": False, "ignore_index": -100},
    lower=_lower_cross_entropy,
    no_grad_inputs=("Label",),
)

register_op(
    "sigmoid_cross_entropy_with_logits",
    inputs=["X", "Label"],
    outputs=["Out"],
    attrs={"ignore_index": -100},
    lower=lambda ctx, ins, attrs: jnp.maximum(ins["X"][0], 0.0)
    - ins["X"][0] * ins["Label"][0]
    + jnp.log1p(jnp.exp(-jnp.abs(ins["X"][0]))),
    no_grad_inputs=("Label",),
)

register_op(
    "bce_loss",
    inputs=["X", "Label"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: -(
        ins["Label"][0] * jnp.log(jnp.maximum(ins["X"][0], 1e-12))
        + (1.0 - ins["Label"][0]) * jnp.log(jnp.maximum(1.0 - ins["X"][0], 1e-12))
    ),
    no_grad_inputs=("Label",),
)


def _lower_squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y
    return {
        "sub_result": d,
        "Out": jnp.sum(jnp.square(d), axis=tuple(range(1, jnp.ndim(d))))[..., None],
    }


register_op(
    "squared_l2_distance",
    inputs=["X", "Y"],
    outputs=["sub_result", "Out"],
    lower=_lower_squared_l2_distance,
    intermediate_outputs=("sub_result",),
)

register_op(
    "squared_l2_norm",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(jnp.sum(jnp.square(ins["X"][0])), (1,)),
)


def _lower_smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    sigma2 = sigma * sigma
    d = x - y
    if "InsideWeight" in ins:
        d = d * ins["InsideWeight"][0]
    abs_d = jnp.abs(d)
    loss = jnp.where(
        abs_d < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(d), abs_d - 0.5 / sigma2
    )
    if "OutsideWeight" in ins:
        loss = loss * ins["OutsideWeight"][0]
    summed = jnp.sum(loss, axis=tuple(range(1, jnp.ndim(loss))))[..., None]
    return {"Diff": d, "Out": summed}


register_op(
    "smooth_l1_loss",
    inputs=["X", "Y", "InsideWeight", "OutsideWeight"],
    outputs=["Diff", "Out"],
    attrs={"sigma": 1.0},
    lower=_lower_smooth_l1,
    no_grad_inputs=("InsideWeight", "OutsideWeight"),
    intermediate_outputs=("Diff",),
)

register_op(
    "huber_loss",
    inputs=["X", "Y"],
    outputs=["Residual", "Out"],
    attrs={"delta": 1.0},
    lower=lambda ctx, ins, attrs: _huber(ins, attrs),
    intermediate_outputs=("Residual",),
)


def _huber(ins, attrs):
    d = ins["Y"][0] - ins["X"][0]
    delta = attrs.get("delta", 1.0)
    abs_d = jnp.abs(d)
    loss = jnp.where(
        abs_d <= delta, 0.5 * jnp.square(d), delta * (abs_d - 0.5 * delta)
    )
    return {"Residual": d, "Out": loss}


register_op(
    "log_loss",
    inputs=["Predicted", "Labels"],
    outputs=["Loss"],
    attrs={"epsilon": 1e-4},
    lower=lambda ctx, ins, attrs: -ins["Labels"][0]
    * jnp.log(ins["Predicted"][0] + attrs.get("epsilon", 1e-4))
    - (1.0 - ins["Labels"][0])
    * jnp.log(1.0 - ins["Predicted"][0] + attrs.get("epsilon", 1e-4)),
    no_grad_inputs=("Labels",),
)

register_op(
    "hinge_loss",
    inputs=["Logits", "Labels"],
    outputs=["Loss"],
    lower=lambda ctx, ins, attrs: jnp.maximum(
        0.0, 1.0 - (2.0 * ins["Labels"][0] - 1.0) * ins["Logits"][0]
    ),
    no_grad_inputs=("Labels",),
)

register_op(
    "rank_loss",
    inputs=["Label", "Left", "Right"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.log1p(
        jnp.exp(ins["Left"][0] - ins["Right"][0])
    )
    - ins["Label"][0] * (ins["Left"][0] - ins["Right"][0]),
    no_grad_inputs=("Label",),
)

register_op(
    "margin_rank_loss",
    inputs=["Label", "X1", "X2"],
    outputs=["Activated", "Out"],
    attrs={"margin": 0.0},
    lower=lambda ctx, ins, attrs: _margin_rank(ins, attrs),
    no_grad_inputs=("Label",),
    intermediate_outputs=("Activated",),
)


def _margin_rank(ins, attrs):
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    out = jnp.maximum(0.0, -label * (x1 - x2) + attrs.get("margin", 0.0))
    return {"Activated": (out > 0).astype(x1.dtype), "Out": out}


register_op(
    "kldiv_loss",
    inputs=["X", "Target"],
    outputs=["Loss"],
    attrs={"reduction": "mean"},
    lower=lambda ctx, ins, attrs: _kldiv(ins, attrs),
    no_grad_inputs=("Target",),
)


def _kldiv(ins, attrs):
    x, t = ins["X"][0], ins["Target"][0]
    loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return jnp.reshape(jnp.mean(loss), (1,))
    if red == "sum":
        return jnp.reshape(jnp.sum(loss), (1,))
    if red == "batchmean":
        return jnp.reshape(jnp.sum(loss) / jnp.shape(x)[0], (1,))
    return loss


def _lower_modified_huber_loss(ctx, ins, attrs):
    """modified_huber_loss_op.cc: binary classification loss on labels
    {0,1} mapped to {-1,+1}. With z = (2y-1)*x: quadratic max(0, 1-z)^2
    for z >= -1, linear -4z beyond (outlier robustness)."""
    x = jnp.reshape(ins["X"][0], (-1,))
    y = jnp.reshape(ins["Y"][0], (-1,)).astype(x.dtype)
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(
        z >= -1.0, jnp.square(jnp.maximum(1.0 - z, 0.0)), -4.0 * z
    )
    shape = (x.shape[0], 1)
    return {
        "Out": jnp.reshape(loss, shape),
        "IntermediateVal": jnp.reshape(z, shape),
    }


register_op(
    "modified_huber_loss",
    inputs=["X", "Y"],
    outputs=["Out", "IntermediateVal"],
    lower=_lower_modified_huber_loss,
    no_grad_inputs=("Y",),
    intermediate_outputs=("IntermediateVal",),
)
