"""Fused ops produced by the fusion passes (core/passes.py).

Reference parity: ``paddle/fluid/operators/fc_op`` (target of
fc_fuse_pass.cc) and ``fused_elemwise_activation_op.cc`` (target of
fuse_elewise_add_act_pass.cc). On TPU the fusion itself is XLA's job —
these ops exist so the *graph* can be collapsed (fewer ops to trace,
fewer intermediate vars to name/GC, parity for the reference's pass
surface); their lowerings are plain compositions XLA fuses to the same
kernels either way.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.ops.common import broadcast_y, flatten_to_2d

# unary functors usable as the activation half of a fused pair; mirrors
# the whitelist in the reference pass (relu/scale/tanh/sigmoid/gelu)
_ACT = {
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "gelu": jax.nn.gelu,
    "identity": lambda x: x,
}


def _lower_fc(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["W"][0]
    x2 = flatten_to_2d(x, attrs.get("in_num_col_dims", 1))
    out = x2 @ w
    bias = ins.get("Bias")
    if bias:
        out = out + bias[0]
    act = attrs.get("activation_type", "")
    if act:
        out = _ACT[act](out)
    n = attrs.get("in_num_col_dims", 1)
    return jnp.reshape(out, tuple(jnp.shape(x)[:n]) + (jnp.shape(w)[1],))


register_op(
    "fc",
    inputs=["Input", "W", "Bias"],
    outputs=["Out"],
    attrs={"in_num_col_dims": 1, "activation_type": ""},
    lower=_lower_fc,
)


def _lower_fused_elemwise_activation(ctx, ins, attrs):
    """out = act(x + y) (functor_list ["elementwise_add", act]); the
    intermediate sum is exported so pre-fusion consumers of the add
    output keep working (save_intermediate_out, reference attr)."""
    functors = list(attrs.get("functor_list", []))
    if len(functors) != 2 or functors[0] != "elementwise_add":
        raise ValueError(
            "fused_elemwise_activation supports functor_list "
            "['elementwise_add', <act>]; got %r" % (functors,))
    act = _ACT[functors[1]]
    x, y = ins["X"][0], ins["Y"][0]
    mid = x + broadcast_y(x, y, attrs.get("axis", -1))
    return {"Out": act(mid), "IntermediateOut": mid}


register_op(
    "fused_elemwise_activation",
    inputs=["X", "Y"],
    outputs=["Out", "IntermediateOut"],
    attrs={"functor_list": [], "axis": -1, "save_intermediate_out": True},
    intermediate_outputs=("IntermediateOut",),
    lower=_lower_fused_elemwise_activation,
)


def _project_then(delegate, ctx, ins, attrs):
    """Shared fusion_lstm/fusion_gru body: input projection
    (X @ WeightX + BiasX) feeding the delegated recurrence lowering, so
    the Pallas-recurrence flags and masking behave identically. BiasX
    holds an absorbed fc bias (the reference pass folds it into the gate
    bias numerically at pass time, which a graph-level pass cannot do
    before startup has run)."""
    x, wx = ins["X"][0], ins["WeightX"][0]
    proj = x @ wx
    bias_x = ins.get("BiasX", [None])[0]
    if bias_x is not None:
        proj = proj + jnp.reshape(bias_x, (-1,))
    inner = dict(ins)
    inner["Input"] = [proj]
    inner["Weight"] = ins["WeightH"]
    return delegate(ctx, inner, attrs)


def _lower_fusion_lstm(ctx, ins, attrs):
    """fusion_lstm_op.cc role."""
    from paddle_tpu.ops.rnn_ops import _lower_dynamic_lstm

    return _project_then(_lower_dynamic_lstm, ctx, ins, attrs)


register_op(
    "fusion_lstm",
    inputs=["X", "WeightX", "WeightH", "Bias", "BiasX", "H0", "C0",
            "Length"],
    outputs=["Hidden", "Cell"],
    attrs={
        "use_peepholes": True,
        "is_reverse": False,
        "gate_activation": "sigmoid",
        "cell_activation": "tanh",
        "candidate_activation": "tanh",
    },
    lower=_lower_fusion_lstm,
    no_grad_inputs=("Length",),
)


def _lower_fusion_gru(ctx, ins, attrs):
    """fusion_gru_op.cc role."""
    from paddle_tpu.ops.rnn_ops import _lower_dynamic_gru

    return _project_then(_lower_dynamic_gru, ctx, ins, attrs)


def _lower_fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """fusion_seqconv_eltadd_relu_op.cc role: sequence_conv + bias add +
    relu in one op; delegates the context-window conv."""
    from paddle_tpu.ops.sequence_ops import _lower_sequence_conv

    out = _lower_sequence_conv(ctx, ins, attrs)["Out"]
    bias = ins.get("Bias", [None])[0]
    if bias is not None:
        out = out + jnp.reshape(bias, (-1,))
    return jax.nn.relu(out)


register_op(
    "fusion_seqconv_eltadd_relu",
    inputs=["X", "Filter", "Bias", "Length"],
    outputs=["Out"],
    attrs={"contextLength": 3, "contextStart": -1, "contextStride": 1},
    lower=_lower_fusion_seqconv_eltadd_relu,
    no_grad_inputs=("Length",),
)


def _lower_fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """fusion_seqexpand_concat_fc_op.cc role: X[0] is the sequence
    [B, T, M0]; every further X[i] is a per-sequence vector [B, Mi]
    broadcast along T (the sequence_expand), all concatenated and run
    through one fc. Dense-padded formulation of the reference's
    LoD-expand + concat + fc chain."""
    xs = ins["X"]
    x0 = xs[0]
    T = jnp.shape(x0)[1]
    cols = [x0]
    for v in xs[1:]:
        cols.append(jnp.broadcast_to(
            v[:, None, :], (jnp.shape(v)[0], T, jnp.shape(v)[1])))
    cat = jnp.concatenate(cols, axis=-1)  # [B, T, sum(Mi)]
    out = cat @ ins["FCWeight"][0]
    bias = ins.get("FCBias", [None])[0]
    if bias is not None:
        out = out + jnp.reshape(bias, (-1,))
    act = attrs.get("fc_activation", "identity")
    return {"Out": _ACT[act](out), "FCOut": out}


register_op(
    "fusion_seqexpand_concat_fc",
    inputs=["*X", "FCWeight", "FCBias"],
    outputs=["Out", "FCOut"],
    attrs={"fc_activation": "identity"},
    intermediate_outputs=("FCOut",),
    lower=_lower_fusion_seqexpand_concat_fc,
)


def _lower_fused_embedding_fc_lstm(ctx, ins, attrs):
    """fused_embedding_fc_lstm_op.cc role: lookup_table + projection fc +
    LSTM recurrence. The reference pass pre-multiplies the table with the
    fc weight numerically at pass time (scope surgery); keeping
    Embeddings and WeightX separate is the graph-level equivalent and
    lets XLA fuse gather + matmul itself."""
    from paddle_tpu.ops.tensor_ops import _lower_lookup_table

    emb = _lower_lookup_table(
        ctx,
        {"W": ins["Embeddings"], "Ids": ins["Ids"]},
        {"padding_idx": attrs.get("padding_idx", -1)},
    )
    inner = dict(ins)
    inner["X"] = [emb]
    return _lower_fusion_lstm(ctx, inner, attrs)


register_op(
    "fused_embedding_fc_lstm",
    inputs=["Ids", "Embeddings", "WeightX", "WeightH", "Bias", "BiasX",
            "H0", "C0", "Length"],
    outputs=["Hidden", "Cell"],
    attrs={
        "use_peepholes": True,
        "is_reverse": False,
        "gate_activation": "sigmoid",
        "cell_activation": "tanh",
        "candidate_activation": "tanh",
        "padding_idx": -1,
    },
    lower=_lower_fused_embedding_fc_lstm,
    no_grad_inputs=("Ids", "Length"),
)


register_op(
    "fusion_gru",
    inputs=["X", "WeightX", "WeightH", "Bias", "BiasX", "H0", "Length"],
    outputs=["Hidden"],
    attrs={
        "is_reverse": False,
        "gate_activation": "sigmoid",
        "activation": "tanh",
    },
    lower=_lower_fusion_gru,
    no_grad_inputs=("Length",),
)
