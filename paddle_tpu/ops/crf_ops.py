"""Linear-chain CRF ops: NLL forward + Viterbi decoding.

Reference parity: ``paddle/fluid/operators/linear_chain_crf_op.cc`` and
``crf_decoding_op.cc`` (used by the label_semantic_roles book chapter). The
reference walks LoD-packed sequences one at a time on the host; here both
the forward (log-partition) and Viterbi recursions are a batched
``lax.scan`` over the padded time axis with length masks, so the [K, K]
transition contraction is one batched matmul per step on the MXU and the
gradient of the NLL comes from jax.vjp over the scan (no manual
beta/backward pass).

Transition layout matches the reference: [num_tags + 2, num_tags], row 0 =
start weights, row 1 = stop weights, rows 2.. = tag-to-tag transitions.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype
from paddle_tpu.ops.common import optional_lengths


def _length_mask(ins, x):
    lens = optional_lengths(ins, x)
    return jnp.arange(jnp.shape(x)[1])[None, :] < lens[:, None]


def _lower_linear_chain_crf(ctx, ins, attrs):
    x = ins["Emission"][0]  # [B, T, K]
    trans = ins["Transition"][0]  # [K+2, K]
    label = ins["Label"][0]  # [B, T] or [B, T, 1]
    label = jnp.reshape(label, (jnp.shape(x)[0], -1))
    length = ins.get("Length", [None])[0]

    B, T, K = jnp.shape(x)[0], jnp.shape(x)[1], jnp.shape(x)[2]
    a = trans[0]  # start [K]
    b = trans[1]  # stop [K]
    w = trans[2:]  # [K, K]
    mask = _length_mask(ins, x).astype(x.dtype)  # [B, T]

    # --- log-partition via forward recursion -----------------------------
    alpha0 = a[None, :] + x[:, 0, :]  # [B, K]

    def fwd(alpha, xm):
        x_t, m_t = xm  # [B, K], [B]
        scores = alpha[:, :, None] + w[None, :, :]  # [B, K, K]
        new = jax.scipy.special.logsumexp(scores, axis=1) + x_t
        new = jnp.where(m_t[:, None] > 0, new, alpha)
        return new, alpha

    xs = jnp.moveaxis(x, 1, 0)[1:]  # [T-1, B, K]
    ms = jnp.moveaxis(mask, 1, 0)[1:]
    alpha_last, alphas = jax.lax.scan(fwd, alpha0, (xs, ms))
    log_z = jax.scipy.special.logsumexp(alpha_last + b[None, :], axis=1)

    # --- gold path score --------------------------------------------------
    emit = jnp.take_along_axis(x, label[:, :, None], axis=2)[:, :, 0]
    emit_score = jnp.sum(emit * mask, axis=1)
    prev_tag = label[:, :-1]
    next_tag = label[:, 1:]
    trans_score = jnp.sum(
        w[prev_tag, next_tag] * mask[:, 1:], axis=1
    )
    start_score = a[label[:, 0]]
    lens_idx = (
        jnp.sum(mask, axis=1).astype(jnp.int32) - 1
        if length is not None
        else jnp.full((B,), T - 1, jnp.int32)
    )
    last_tag = jnp.take_along_axis(label, lens_idx[:, None], axis=1)[:, 0]
    stop_score = b[last_tag]
    gold = emit_score + trans_score + start_score + stop_score

    nll = (log_z - gold)[:, None]  # [B, 1]
    full_alpha = jnp.concatenate(
        [jnp.moveaxis(alphas, 0, 1), alpha_last[:, None, :]], axis=1
    )
    return {
        "Alpha": full_alpha,
        "EmissionExps": jnp.exp(x),
        "TransitionExps": jnp.exp(trans),
        "LogLikelihood": nll,
    }


register_op(
    "linear_chain_crf",
    inputs=["Emission", "Transition", "Label", "Length"],
    outputs=["Alpha", "EmissionExps", "TransitionExps", "LogLikelihood"],
    lower=_lower_linear_chain_crf,
    no_grad_inputs=("Label", "Length"),
    intermediate_outputs=("Alpha", "EmissionExps", "TransitionExps"),
)


def _lower_crf_decoding(ctx, ins, attrs):
    x = ins["Emission"][0]  # [B, T, K]
    trans = ins["Transition"][0]
    length = ins.get("Length", [None])[0]
    B, T, K = jnp.shape(x)[0], jnp.shape(x)[1], jnp.shape(x)[2]
    a, b, w = trans[0], trans[1], trans[2:]
    mask = _length_mask(ins, x)
    lens_idx = jnp.sum(mask.astype(jnp.int32), axis=1) - 1  # [B]

    delta0 = a[None, :] + x[:, 0, :]

    def fwd(delta, xm):
        x_t, m_t = xm
        scores = delta[:, :, None] + w[None, :, :]  # [B, K(prev), K(cur)]
        best_prev = jnp.argmax(scores, axis=1)  # [B, K]
        new = jnp.max(scores, axis=1) + x_t
        new = jnp.where(m_t[:, None], new, delta)
        return new, best_prev

    xs = jnp.moveaxis(x, 1, 0)[1:]
    ms = jnp.moveaxis(mask, 1, 0)[1:]
    delta_last, bps = jax.lax.scan(fwd, delta0, (xs, ms))
    # bps[t] holds backpointers for step t+1; [T-1, B, K]
    best_last = jnp.argmax(delta_last + b[None, :], axis=1).astype(jnp.int32)

    def back(carry, t):
        # carry = tag at position t+1; bps[t] holds position t+1's
        # backpointers. Positions at/after len-1 pin to the final best tag
        # so the carry is already best_last when the backtrack reaches the
        # row's true last position.
        tag_here = jnp.take_along_axis(
            bps[t], carry[:, None], axis=1
        )[:, 0].astype(jnp.int32)
        tag = jnp.where(t >= lens_idx, best_last, tag_here)
        return tag, tag

    _, path_rev = jax.lax.scan(
        back, best_last, jnp.arange(T - 2, -1, -1)
    )
    # path_rev[i] = tag at position T-2-i  ->  [B, T-1] forward order.
    body = jnp.flip(jnp.moveaxis(path_rev, 0, 1), axis=1)
    path = jnp.concatenate([body, best_last[:, None]], axis=1)  # [B, T]
    path = jnp.where(mask, path, 0).astype(device_dtype("int64"))

    label = ins.get("Label", [None])[0]
    if label is not None:
        label = jnp.reshape(label, (B, -1))
        path = jnp.where(mask, (path == label).astype(device_dtype("int64")), 0)
    return {"ViterbiPath": path}


register_op(
    "crf_decoding",
    inputs=["Emission", "Transition", "Label", "Length"],
    outputs=["ViterbiPath"],
    lower=_lower_crf_decoding,
    grad=None,
)
