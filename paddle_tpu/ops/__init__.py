"""Operator definitions: schema + XLA lowering per op family.

Reference parity: ``paddle/fluid/operators/`` (~748 files). Importing this
package registers every op with the registry; the kernel body of each op is
a JAX/XLA lowering (and Pallas for hand-tuned hot paths) instead of
CPU/CUDA kernels.
"""

from paddle_tpu.ops import math_ops  # noqa: F401
from paddle_tpu.ops import tensor_ops  # noqa: F401
from paddle_tpu.ops import activation_ops  # noqa: F401
from paddle_tpu.ops import random_ops  # noqa: F401
from paddle_tpu.ops import loss_ops  # noqa: F401
from paddle_tpu.ops import nn_ops  # noqa: F401
from paddle_tpu.ops import optimizer_ops  # noqa: F401
from paddle_tpu.ops import control_flow_ops  # noqa: F401
from paddle_tpu.ops import subblock_ops  # noqa: F401
from paddle_tpu.ops import rnn_ops  # noqa: F401
from paddle_tpu.ops import attention_ops  # noqa: F401
from paddle_tpu.ops import sequence_ops  # noqa: F401
from paddle_tpu.ops import metric_ops  # noqa: F401
from paddle_tpu.ops import io_ops  # noqa: F401
from paddle_tpu.ops import detection_ops  # noqa: F401
from paddle_tpu.ops import beam_search_ops  # noqa: F401
from paddle_tpu.ops import seq2seq_ops  # noqa: F401
from paddle_tpu.ops import crf_ops  # noqa: F401
from paddle_tpu.ops import ctc_ops  # noqa: F401
from paddle_tpu.ops import sampling_ops  # noqa: F401
from paddle_tpu.ops import speculative_ops  # noqa: F401
from paddle_tpu.ops import vision_ops  # noqa: F401
from paddle_tpu.ops import quantize_ops  # noqa: F401
from paddle_tpu.ops import fused_ops  # noqa: F401
from paddle_tpu.ops import moe_ops  # noqa: F401
