"""Sub-block mega-ops: recurrent (StaticRNN), cond, while, tensor arrays.

Reference parity: ``paddle/fluid/operators/recurrent_op.cc`` (static RNN
over StepScopes), ``while_op.cc:36``, ``conditional_block_op.cc``, and the
tensor-array ops (``tensor_array_read_write_op.cc``). The reference runs a
nested Executor per iteration and records StepScopes for the backward pass;
the TPU-first lowering traces the sub-block ONCE into the body of
``lax.scan`` / ``lax.while_loop`` / ``lax.cond``, so the whole loop compiles
into a single XLA While/Conditional and the backward pass of ``recurrent``
is jax.vjp over scan — no scope replay (SURVEY.md §7 hard part (g)).

Conventions:
  * sequence tensors are [batch, T, ...]; scan runs time-major internally.
  * carried state must be shape-invariant (XLA constraint).
  * tensor arrays are (buffer[capacity, ...], size:int32) pytree pairs.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.op_registry import register_op
from paddle_tpu.core.types import device_dtype


def _sub_lowerer(ctx, block_idx):
    from paddle_tpu.core.lowering import BlockLowerer

    parent = ctx.block_lowerer
    return BlockLowerer(parent.program, block_idx, is_test=parent.is_test)


def _run_block(sub, env, key):
    for op in sub.block.ops:
        sub.lower_op(op, env, key)
    return env


# ---------------------------------------------------------------------------
# recurrent — scan-based StaticRNN
# ---------------------------------------------------------------------------


def _lower_recurrent(ctx, ins, attrs):
    sub = _sub_lowerer(ctx, attrs["sub_block"])
    in_names = list(attrs.get("input_step_names", []))
    pre_names = list(attrs.get("pre_state_names", []))
    state_names = list(attrs.get("state_names", []))
    out_names = list(attrs.get("output_step_names", []))
    param_names = list(attrs.get("param_names", []))
    reverse = attrs.get("reverse", False)

    seq_inputs = ins.get("inputs", [])
    init_states = ins.get("initial_states", [])
    params = ins.get("parameters", [])
    base_key = ctx.rng()

    xs = tuple(jnp.moveaxis(x, 1, 0) for x in seq_inputs)  # [T, B, ...]
    if reverse:
        xs = tuple(jnp.flip(x, axis=0) for x in xs)

    def body(carry, x_ts):
        t, states = carry
        key = jax.random.fold_in(base_key, t)
        env = dict(zip(param_names, params))
        env.update(zip(pre_names, states))
        env.update(zip(in_names, x_ts))
        _run_block(sub, env, key)
        new_states = tuple(env[n] for n in state_names)
        ys = tuple(env[n] for n in out_names)
        return (t + 1, new_states), ys

    (_, final_states), ys = jax.lax.scan(
        body, (jnp.asarray(0, jnp.int32), tuple(init_states)), xs
    )
    outputs = [jnp.moveaxis(y, 0, 1) for y in ys]
    if reverse:
        outputs = [jnp.flip(y, axis=1) for y in outputs]
    return {"outputs": outputs, "final_states": list(final_states)}


register_op(
    "recurrent",
    inputs=["*inputs", "*initial_states", "*parameters"],
    outputs=["*outputs", "*final_states"],
    attrs={
        "sub_block": -1,
        "input_step_names": [],
        "pre_state_names": [],
        "state_names": [],
        "output_step_names": [],
        "param_names": [],
        "reverse": False,
    },
    lower=_lower_recurrent,
)


# ---------------------------------------------------------------------------
# cond — two-branch conditional (conditional_block/IfElse capability)
# ---------------------------------------------------------------------------


def _lower_cond(ctx, ins, attrs):
    """lax.cond over two sub-blocks. Both branches must produce the declared
    output names with matching shapes (XLA conditional contract)."""
    input_names = list(attrs.get("input_names", []))
    true_outs = list(attrs.get("true_out_names", []))
    false_outs = list(attrs.get("false_out_names", []))
    sub_t = _sub_lowerer(ctx, attrs["true_block"])
    sub_f = _sub_lowerer(ctx, attrs["false_block"])
    xs = ins.get("X", [])
    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    key = ctx.rng()

    def branch(sub, out_names):
        def fn(args):
            env = dict(zip(input_names, args))
            _run_block(sub, env, key)
            return tuple(env[n] for n in out_names)

        return fn

    outs = jax.lax.cond(
        pred, branch(sub_t, true_outs), branch(sub_f, false_outs), tuple(xs)
    )
    return {"Out": list(outs)}


register_op(
    "cond",
    inputs=["Cond", "*X"],
    outputs=["*Out"],
    attrs={
        "true_block": -1,
        "false_block": -1,
        "input_names": [],
        "true_out_names": [],
        "false_out_names": [],
    },
    lower=_lower_cond,
    no_grad_inputs=("Cond",),
)


# ---------------------------------------------------------------------------
# while — lax.while_loop over a sub-block (forward-only, while_op.cc parity)
# ---------------------------------------------------------------------------


def _lower_while(ctx, ins, attrs):
    """Carried state = the declared carry vars (attr carry_names), which the
    sub-block reads and writes; Condition is one of them (a [1] bool).

    Two lowerings (SURVEY §7 hard part (g), while_op.cc:50-72 StepScopes
    backward redesigned graph-level):

    - ``max_iterations > 0``: a masked ``lax.scan`` over the static bound —
      iterations past loop exit are no-ops via jnp.where select, so the
      result is identical to the dynamic loop AND reverse-mode autodiff
      works (the synthesized ``while_grad`` re-traces this rule under
      jax.vjp; scan stores per-iteration residuals instead of the
      reference's StepScopes).
    - ``max_iterations == 0``: a ``lax.while_loop`` — cheapest for
      inference decode loops with early exit, but forward-only (XLA cannot
      reverse-differentiate an unbounded loop; set max_iterations to train
      through a While).
    """
    carry_names = list(attrs.get("carry_names", []))
    param_names = list(attrs.get("param_names", []))
    cond_name = attrs["cond_name"]
    sub = _sub_lowerer(ctx, attrs["sub_block"])
    carries = ins.get("X", [])
    params = ins.get("parameters", [])
    base_key = ctx.rng()

    max_iters = attrs.get("max_iterations", 0)

    if max_iters:
        def step(vals, t):
            env = dict(zip(param_names, params))
            env.update(zip(carry_names, vals))
            active = jnp.reshape(env[cond_name], ()).astype(bool)
            _run_block(sub, env, jax.random.fold_in(base_key, t))
            new_vals = tuple(env[n] for n in carry_names)
            sel = jax.tree.map(
                lambda a, b: jnp.where(active, a, b), new_vals, tuple(vals)
            )
            return sel, None

        final, _ = jax.lax.scan(
            step, tuple(carries), jnp.arange(max_iters, dtype=jnp.int32)
        )
        return {"Out": list(final), "InitX": list(carries)}

    def cond_fn(state):
        t, vals = state
        env = dict(zip(carry_names, vals))
        return jnp.reshape(env[cond_name], ()).astype(bool)

    def body_fn(state):
        t, vals = state
        env = dict(zip(param_names, params))
        env.update(zip(carry_names, vals))
        _run_block(sub, env, jax.random.fold_in(base_key, t))
        return (t + 1, tuple(env[n] for n in carry_names))

    _, final = jax.lax.while_loop(
        cond_fn, body_fn, (jnp.asarray(0, jnp.int32), tuple(carries))
    )
    return {"Out": list(final), "InitX": list(carries)}


def _while_grad_maker(op, out_grads, wanted):
    """while's Out aliases X (in-place carries), so by grad time the env
    holds POST-loop values under those names; the InitX outputs saved the
    pre-loop carries under fresh names (graph-level StepScopes,
    while_op.cc:50-72), and while_grad re-runs the bounded scan from them
    under jax.vjp."""
    inputs = {
        "InitX": list(op.output("InitX")),
        "parameters": list(op.input("parameters")),
        "Out@GRAD": [g or "" for g in out_grads.get("Out", [])],
    }
    outputs = {}
    if "X" in wanted:
        outputs["X@GRAD"] = wanted["X"]
    if "parameters" in wanted:
        outputs["parameters@GRAD"] = wanted["parameters"]
    keep = ("sub_block", "carry_names", "param_names", "cond_name",
            "max_iterations")
    return [{
        "type": "while_grad",
        "inputs": inputs,
        "outputs": outputs,
        "attrs": {k: op.attrs[k] for k in keep if k in op.attrs},
    }]


def _lower_while_grad(ctx, ins, attrs):
    from paddle_tpu.core.op_registry import get_op_def, lower_grad_via_vjp

    if not attrs.get("max_iterations", 0):
        raise RuntimeError(
            "cannot differentiate a While with max_iterations=0: the "
            "unbounded lax.while_loop lowering is forward-only. Build the "
            "loop as fluid.layers.While(cond, max_iterations=N) to train "
            "through it (bounded masked-scan lowering)."
        )
    op = ctx.op
    init = ins.get("InitX", [])
    params = ins.get("parameters", [])
    out_gs = ins.get("Out@GRAD", [])
    wanted = {}
    xg = op.output("X@GRAD")
    pg = op.output("parameters@GRAD")
    if any(xg):
        wanted["X"] = [bool(n) for n in xg]
    if any(pg):
        wanted["parameters"] = [bool(n) for n in pg]
    gres = lower_grad_via_vjp(
        get_op_def("while"), ctx, {"X": init, "parameters": params}, attrs,
        {"Out": out_gs}, wanted,
    )
    out = {}
    if "X" in gres:
        out["X@GRAD"] = gres["X"]
    if "parameters" in gres:
        out["parameters@GRAD"] = gres["parameters"]
    return out


register_op(
    "while",
    inputs=["*X", "*parameters"],
    outputs=["*Out", "*InitX"],
    attrs={
        "sub_block": -1,
        "carry_names": [],
        "param_names": [],
        "cond_name": "",
        "max_iterations": 0,
    },
    lower=_lower_while,
    grad=_while_grad_maker,
    intermediate_outputs=("InitX",),
)


register_op(
    "while_grad",
    inputs=["*InitX", "*parameters", "*Out@GRAD"],
    outputs=["*X@GRAD", "*parameters@GRAD"],
    attrs={
        "sub_block": -1,
        "carry_names": [],
        "param_names": [],
        "cond_name": "",
        "max_iterations": 0,
    },
    lower=_lower_while_grad,
    grad=None,
)


# ---------------------------------------------------------------------------
# tensor arrays — (buffer, size) pairs with static capacity
# ---------------------------------------------------------------------------


def _lower_write_to_array(ctx, ins, attrs):
    x = ins["X"][0]
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32)
    arr = ins.get("Array", [None])
    if arr and arr[0] is not None:
        buf, size = arr[0]
    else:
        cap = int(attrs.get("capacity", 0))
        if cap <= 0:
            raise ValueError(
                "first write_to_array needs a static 'capacity' attr "
                "(XLA needs fixed buffer shapes)"
            )
        buf = jnp.zeros((cap,) + tuple(jnp.shape(x)), x.dtype)
        size = jnp.asarray(0, jnp.int32)
    # Out-of-capacity writes are dropped (XLA's dynamic_update clamps OOB
    # indices, which would silently overwrite the last slot instead).
    cap = jnp.shape(buf)[0]
    written = jax.lax.dynamic_update_index_in_dim(
        buf, x, jnp.minimum(i, cap - 1), axis=0
    )
    in_bounds = i < cap
    buf = jnp.where(in_bounds, written, buf)
    size = jnp.where(
        in_bounds, jnp.maximum(size, i + 1), size
    ).astype(jnp.int32)
    return {"Out": [(buf, size)]}


register_op(
    "write_to_array",
    inputs=["X", "I", "Array"],
    outputs=["Out"],
    attrs={"capacity": 0},
    lower=_lower_write_to_array,
    grad=None,
)


register_op(
    "read_from_array",
    inputs=["X", "I"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jax.lax.dynamic_index_in_dim(
        ins["X"][0][0],
        jnp.reshape(ins["I"][0], ()).astype(jnp.int32),
        axis=0,
        keepdims=False,
    ),
    grad=None,
)


register_op(
    "lod_array_length",
    inputs=["X"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: jnp.reshape(
        ins["X"][0][1].astype(device_dtype("int64")), (1,)
    ),
    grad=None,
)


def _array_to_lod_tensor_grad_maker(op, out_grads, wanted):
    # The inverse re-axing: dX (an array composite) = lod_tensor_to_array
    # of the dense dOut. The pair makes the round trip differentiable
    # (reference: array_to_lod_tensor_op.cc's grad is lod_tensor_to_array).
    return [{
        "type": "lod_tensor_to_array",
        "inputs": {"X": [out_grads["Out"][0]],
                   "RankTable": list(op.input("RankTable"))},
        "outputs": {"Out": wanted["X"]},
        "attrs": {},
    }]


register_op(
    "array_to_lod_tensor",
    inputs=["X", "RankTable"],
    outputs=["Out"],
    # Stacked time-major array buffer [cap, B, ...] -> dense batch-major
    # [B, cap, ...] tensor, inverting lod_tensor_to_array. Unwritten slots
    # past the array's size remain zero padding (dense-padded regime; the
    # reference's LoD restore re-packs ragged rows instead).
    lower=lambda ctx, ins, attrs: jnp.moveaxis(ins["X"][0][0], 0, 1),
    grad=_array_to_lod_tensor_grad_maker,
    no_grad_inputs=("RankTable",),
)


def _lod_tensor_to_array_grad_maker(op, out_grads, wanted):
    # dX (dense) = array_to_lod_tensor of the array-composite grad.
    return [{
        "type": "array_to_lod_tensor",
        "inputs": {"X": [out_grads["Out"][0]],
                   "RankTable": list(op.input("RankTable"))},
        "outputs": {"Out": wanted["X"]},
        "attrs": {},
    }]


register_op(
    "lod_tensor_to_array",
    inputs=["X", "RankTable"],
    outputs=["Out"],
    lower=lambda ctx, ins, attrs: {
        "Out": [
            (
                jnp.moveaxis(ins["X"][0], 1, 0),
                jnp.asarray(jnp.shape(ins["X"][0])[1], jnp.int32),
            )
        ]
    },
    grad=_lod_tensor_to_array_grad_maker,
    no_grad_inputs=("RankTable",),
)
